"""Streamed TPU-side ingest: device binning with a double-buffered
host->device chunk pipeline.

The host binner (io/binning.py + the threaded C++ bulk binner) maps
values to bins one full column scan at a time while the TPU idles; at
HIGGS scale that is ~29 s of binning against ~112 s of training. This
module moves the value->bin mapping onto the device, mirroring the
reference's streamed two-round ingest design
(DatasetLoader::ConstructFromSampleData, src/io/dataset_loader.cpp:499:
bin boundaries from a bounded ``bin_construct_sample_cnt`` sample, then
a streaming pass that bins rows as they arrive):

- bin boundaries still come from the bounded row sample
  (io/dataset.py find_column_mappers — unchanged semantics);
- the value->bin map runs on device as a jitted chunked kernel: a
  branchless lower-bound search over per-feature ``bin_upper_bound``
  plus the missing/zero-bin/categorical rules of
  ``BinMapper.value_to_bin``, BIT-EXACT against the host path (see
  "exactness" below);
- raw row chunks stream host->device double-buffered: a worker thread
  prepares chunk k+1 (column select, key planes) while chunk k's
  async ``device_put`` + kernel dispatch are in flight, so transfer
  overlaps compute and the full host uint8 matrix + transpose + bulk
  upload disappear from the critical path;
- the feature-major ``[F, N]`` ``bins_t`` matrix is assembled directly
  on device (one concatenate over chunk outputs), which is exactly the
  layout the wave grower consumes (models/gbdt.py);
- when the configured tree learner row-shards (``tree_learner`` data /
  voting over a >1-device mesh), ``bin_matrix_sharded`` round-robins
  the chunk pipeline ACROSS the mesh and assembles the matrix directly
  under the grower's ``NamedSharding`` — each device receives and bins
  only its own contiguous row block, so no single-device staging copy
  of the dataset ever exists (Design.md §7).

Exactness
---------
jax runs with x64 disabled, so comparing values against the float64
``bin_upper_bound`` cannot use device floats directly. Instead every
comparison is done in the *sortable-integer* order of IEEE-754: a
float maps to an unsigned key (sign bit flipped for positives, all
bits flipped for negatives) whose integer order equals the float
order. Two cases:

- float32 input: keys are computed ON DEVICE from the raw f32 bits;
  each float64 bound is rounded DOWN to float32 first. For any f32
  value x and f64 bound b, ``b < x  <=>  floor32(b) < x`` (the largest
  f32 <= b preserves the strict predicate over f32 operands), so the
  f32 key search reproduces the f64 ``searchsorted(..., side="left")``
  exactly.
- float64 input: the host splits each value's 64-bit key into two
  uint32 planes (same bytes on the wire as the raw f64) and the device
  compares lexicographically — exact total order, no rounding anywhere.

``-0.0`` is normalized to ``+0.0`` (``v + 0.0``) on both sides before
key extraction: numpy's searchsorted treats them as equal while the
key order would not, and the zero-as-one-bin boundaries sit at
±kZeroThreshold right next to that crossing.

NaN follows ``value_to_bin``: mapped as 0.0, then overridden to the
last bin for MissingType.NAN features. Categorical columns are
truncated to int on host (few columns, cheap) and matched against the
category table on device.
"""
from __future__ import annotations

import collections
import concurrent.futures
import threading
from typing import List, Optional, Sequence

import numpy as np

from ..analysis import lockorder
from ..obs import registry as obs
from ..obs import trace
from ..utils import log, timing
from .binning import BinMapper, BinType, MissingType

_TARGET_CHUNK_BYTES = 64 << 20      # ~64 MB of raw values per chunk
_MIN_CHUNK_ROWS = 1 << 14
_MAX_CHUNK_ROWS = 1 << 21


class IngestUnsupported(Exception):
    """Raised at DeviceBinner construction when the mapper set has a
    shape the device kernel cannot reproduce bit-exactly (callers fall
    back to the host binner)."""


def ingest_enabled(config) -> bool:
    """Config gate: tpu_ingest=1 forces the device path on any backend
    (tests), 0 disables, -1 (default) auto-enables on a real TPU."""
    t = getattr(config, "tpu_ingest", -1)
    if t == 0:
        return False
    if t >= 1:
        return True
    from ..utils.device import on_tpu
    return on_tpu()


def ingest_mesh(config):
    """The device mesh sharded ingest should target, or None for the
    single-device pipeline: the configured tree learner must row-shard
    (data/voting) over more than one device. Uses the SAME mesh
    construction as the learners (parallel/learners.py make_mesh), so
    the [F, N] bins land exactly where the shard_mapped grower will
    read them — no single-device staging, no per-iteration reshard."""
    if getattr(config, "tree_learner", "serial") not in ("data",
                                                         "voting"):
        return None
    from ..parallel.learners import training_mesh
    return training_mesh(config)


def shard_width(n: int, D: int, hist_chunk: int = 0) -> int:
    """Per-device row-shard width S for ``n`` global rows over ``D``
    mesh devices: device (mesh position) gd owns global rows
    [gd*S, (gd+1)*S). Each shard aligns to the grower's row chunk so
    _setup_grower ADOPTS this padding instead of re-padding +
    resharding the whole mesh-resident matrix: the pinned
    tpu_hist_chunk when set, else the LARGEST power-of-two unit
    u <= MAX_HIST_CHUNK (the autotune candidate ceiling, exhaustive
    tier included) with n >= 4*D*u — the grower only chunk-aligns when
    n >= 4*D*kchunk, so every kchunk it can align to satisfies
    kchunk <= u and (both powers of two) divides S; pad stays <= S/4
    by the same bound. ONE function for the single-process sharded
    path, the multi-process per-host path, and the loader's host
    row-block slicing (io/distributed.py) — their geometries cannot
    drift."""
    S = max(-(-int(n) // int(D)), 1)
    from ..ops.autotune import MAX_HIST_CHUNK
    if hist_chunk > 0:
        u = hist_chunk if n >= 4 * D * hist_chunk else 1
    else:
        u = 1
        while u * 2 <= MAX_HIST_CHUNK and n >= 4 * D * (u * 2):
            u *= 2
    if u > 1:
        S = -(-S // u) * u
    return S


def host_row_block(n_global: int, mesh, hist_chunk: int = 0) -> tuple:
    """(row_start, row_stop, S) — the contiguous GLOBAL row range this
    process must hold so its addressable devices' shard blocks are
    coverable by bin_matrix_multihost (row_stop clamps to n_global)."""
    import jax
    positions = list(mesh.devices.reshape(-1))
    S = shard_width(n_global, len(positions), hist_chunk)
    proc = jax.process_index()
    owned = [gd for gd, dev in enumerate(positions)
             if dev.process_index == proc]
    if not owned:
        return 0, 0, S
    lo = min(owned) * S
    hi = min((max(owned) + 1) * S, int(n_global))
    return min(lo, int(n_global)), hi, S


def mappers_supported(mappers: Sequence[BinMapper]) -> bool:
    """True when every mapper is reproducible on device: categorical
    tables must fit int32 (host matching runs at int64)."""
    for m in mappers:
        if m.bin_type == BinType.CATEGORICAL:
            if any(abs(int(c)) >= 2 ** 31 for c in m.bin_2_categorical):
                return False
    return True


def auto_chunk_rows(config, n_features: int, itemsize: int) -> int:
    """Rows per pipeline chunk: the config knob, or a power of two
    sized so one chunk's raw values are ~64 MB on the wire."""
    knob = int(getattr(config, "tpu_ingest_chunk_rows", 0) or 0)
    if knob > 0:
        return knob
    per_row = max(n_features * itemsize, 1)
    c = max(_TARGET_CHUNK_BYTES // per_row, 1)
    c = 1 << int(np.floor(np.log2(c)))
    return int(min(max(c, _MIN_CHUNK_ROWS), _MAX_CHUNK_ROWS))


class PrefetchError(RuntimeError):
    """A prefetch thunk failed after retries; the message carries the
    chunk index so a dead pipeline names WHERE it died. The original
    failure rides ``__cause__``."""


def prefetch(thunks, depth: int = 2, what: str = "chunk",
             policy=None):
    """Evaluate an iterator of zero-arg callables on ONE worker thread
    with a bounded lookahead, yielding results in order — the host
    half of the double buffer: while the device chews on chunk k, the
    worker slices/keys chunk k+1. One thread is deliberate: host prep
    is memory-bandwidth bound and the results must stay ordered.

    Fault tolerance: each thunk runs under the bounded-backoff retry
    policy (utils/retry.py; ``policy`` — e.g. the DeviceBinner's
    ``tpu_retry_attempts``-sized one — or the module default when
    None, so transient failures recover in place on the worker). A
    persistent failure surfaces as a ``PrefetchError`` naming the
    failed chunk's index, every queued lookahead future is cancelled,
    and the worker shuts down cleanly — the pipeline never
    half-drains past a dead chunk."""
    from ..utils import retry
    it = iter(thunks)
    with concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="ingest-prefetch") as ex:
        q: collections.deque = collections.deque()  # (index, future)
        submitted = 0

        def submit() -> bool:
            nonlocal submitted
            try:
                thunk = next(it)
            except StopIteration:
                return False
            idx = submitted
            submitted += 1
            q.append((idx, ex.submit(
                retry.call, thunk, what=f"{what} {idx}",
                policy=policy)))
            return True

        try:
            for _ in range(max(depth, 1)):
                if not submit():
                    break
            while q:
                idx, fut = q.popleft()
                submit()
                try:
                    yield fut.result()
                except Exception as e:  # noqa: BLE001 — annotate+stop
                    raise PrefetchError(
                        f"{what} {idx} failed after retries "
                        f"({type(e).__name__}: {e}); pipeline "
                        f"cancelled") from e
        finally:
            for _, f in q:
                f.cancel()


# -- device-resident chunk ring ----------------------------------------------

# upload-region bucket floor: small windows re-pad to at most this many
# rows, so the splice shapes (and their compiled programs) stay few
_RING_UPLOAD_FLOOR = 256


def ring_upload_rows(k: int, prev_valid: int, chunk_rows: int) -> int:
    """Rows the ring path actually uploads for a chunk carrying ``k``
    live rows over a slot whose previous occupant had ``prev_valid``:
    the next power of two covering BOTH (stale rows of a larger
    previous window must be overwritten with pad constants), floored
    at ``_RING_UPLOAD_FLOOR`` and capped at the full chunk."""
    u = max(k, prev_valid, 1)
    b = _RING_UPLOAD_FLOOR
    while b < u:
        b *= 2
    return min(b, chunk_rows)


class ChunkRing:
    """Bounded ring of device-RESIDENT raw ingest chunks, reused across
    dataset constructions of the same chunk geometry — the lrb.py
    sliding-window loop's training matrix.

    The streamed ingest pipeline pads every chunk to the binner's fixed
    ``chunk_rows`` on the HOST so all chunks share one compiled kernel;
    for a sample-sized window that means most of the transfer is pad
    bytes, re-uploaded every window. With a ring, each chunk slot keeps
    its last assembled device transfer tuple resident; the next window
    uploads only the bucketed live-row region (``ring_upload_rows``)
    and the resident tail — whose rows are pad constants by the
    invariant below — is spliced back on device. The raw value/key
    planes are MAPPER-INDEPENDENT, so a fresh window's fresh bin
    mappers bin the resident rows exactly as a full re-upload would:
    training results are bit-identical.

    Invariant: every resident array's rows at index >= its recorded
    ``valid`` row count hold the host binner's pad constants (zeros;
    -1 for the categorical plane). Maintained because each upload
    region covers ``max(k, prev_valid)`` rows and carries those same
    constants beyond row ``k``.

    Slots are keyed by chunk index and guarded by the binner's chunk
    geometry key — a dataset with a different chunk shape simply
    misses. Thread-safe: the lrb trainer thread ingests while the main
    thread may be building the next window's ring-less eval batches.
    """

    def __init__(self, capacity: int = 8):
        self._lock = lockorder.named_lock("ingest.chunk_ring._lock")
        self._cap = max(int(capacity), 1)
        # guarded-by: _lock
        self._slots: "collections.OrderedDict[int, tuple]" = \
            collections.OrderedDict()

    @property
    def capacity(self) -> int:
        return self._cap

    def get(self, slot: int, geom_key) -> tuple:
        """-> (resident arrays tuple or None, valid_rows)."""
        with self._lock:
            ent = self._slots.get(slot)
            if ent is None or ent[0] != geom_key:
                return None, 0
            self._slots.move_to_end(slot)
            return ent[1], ent[2]

    def put(self, slot: int, geom_key, arrays, valid: int) -> None:
        with self._lock:
            self._slots[slot] = (geom_key, arrays, int(valid))
            self._slots.move_to_end(slot)
            while len(self._slots) > self._cap:
                self._slots.popitem(last=False)

    def clear(self) -> None:
        with self._lock:
            self._slots.clear()


# -- sortable-integer float keys --------------------------------------------

def _keys64_host(v: np.ndarray):
    """float64 [..] -> (hi, lo) uint32 key planes, integer order ==
    float order (NaN-free input)."""
    b = np.ascontiguousarray(v, np.float64).view(np.uint64)
    neg = (b >> np.uint64(63)).astype(bool)
    mask = np.where(neg, np.uint64(0xFFFFFFFFFFFFFFFF),
                    np.uint64(0x8000000000000000))
    u = b ^ mask
    return ((u >> np.uint64(32)).astype(np.uint32),
            u.astype(np.uint32))


def _key32_host(v: np.ndarray) -> np.ndarray:
    """float32 [..] -> uint32 key (NaN-free input)."""
    b = np.ascontiguousarray(v, np.float32).view(np.uint32)
    neg = (b >> np.uint32(31)).astype(bool)
    mask = np.where(neg, np.uint32(0xFFFFFFFF), np.uint32(0x80000000))
    return b ^ mask


def _floor32(b64: np.ndarray) -> np.ndarray:
    """Largest float32 <= each float64 entry (rounds DOWN, so the
    strict `bound < x` predicate is preserved for float32 x)."""
    f = b64.astype(np.float32)
    over = f.astype(np.float64) > b64
    down = np.nextafter(f, np.float32(-np.inf))
    return np.where(over, down, f).astype(np.float32)


def _cat_iv_host(col: np.ndarray) -> np.ndarray:
    """Host half of the categorical map: truncate toward zero to int32
    with NaN/out-of-range -> -1 (never a category; negatives were
    NaN-ified at find_bin time, bin.cpp:304)."""
    col = np.asarray(col, np.float64)
    with np.errstate(invalid="ignore"):
        bad = np.isnan(col) | (np.abs(col) >= 2.0 ** 31)
        safe = np.where(bad, -1.0, col)
    return safe.astype(np.int64).astype(np.int32)


# -- the device binner -------------------------------------------------------

class DeviceBinner:
    """Jitted chunked value->bin kernel for one mapper set.

    Built once per dataset; ``bin_matrix`` (whole in-memory matrix,
    threaded prefetch) and ``start_stream`` (two-round loader feed)
    share the same compiled chunk function. ``x_dtype`` selects the
    exact-comparison scheme (see module docstring)."""

    def __init__(self, mappers: List[BinMapper],
                 used_feature_map: np.ndarray, config,
                 x_dtype) -> None:
        import jax.numpy as jnp
        if not mappers:
            raise IngestUnsupported("no usable features")
        if not mappers_supported(mappers):
            raise IngestUnsupported("categorical table exceeds int32")
        x_dtype = np.dtype(x_dtype)
        if x_dtype not in (np.float32, np.float64):
            raise IngestUnsupported(f"dtype {x_dtype} not supported")
        self.mappers = mappers
        self.f32_input = x_dtype == np.float32
        used = np.asarray(used_feature_map, np.int64)
        self.num_inner = [i for i, m in enumerate(mappers)
                          if m.bin_type == BinType.NUMERICAL]
        self.cat_inner = [i for i, m in enumerate(mappers)
                         if m.bin_type != BinType.NUMERICAL]
        self.num_cols = used[self.num_inner]       # real/source columns
        self.cat_cols = used[self.cat_inner]
        max_bin_global = max(m.num_bin for m in mappers)
        self.out_dtype = np.uint8 if max_bin_global <= 256 else np.int32
        self.chunk_rows = auto_chunk_rows(config, len(mappers),
                                          x_dtype.itemsize)
        # explicit Pallas row chunk, when the operator pinned one —
        # lets sharded ingest align shards to the exact chunk the
        # grower will use instead of the 32k candidate superset
        self.hist_chunk = int(getattr(config, "tpu_hist_chunk", 0) or 0)
        # transient-failure policy for this pipeline's prep + transfer
        # seams: attempts come from the tpu_retry_attempts knob
        from ..utils import retry
        self.retry_policy = retry.RetryPolicy(
            attempts=int(getattr(config, "tpu_retry_attempts", 4) or 4))

        # numerical tables: per-feature search range r, NaN bin, and the
        # bound keys padded to a power of two with the max key (never
        # `< x`, so padding never counts)
        rs, nan_bins, bounds = [], [], []
        for i in self.num_inner:
            m = mappers[i]
            r = m.num_bin - 1
            nb = -1
            if m.missing_type == MissingType.NAN:
                r -= 1
                nb = m.num_bin - 1
            rs.append(r)
            nan_bins.append(nb)
            bounds.append(np.asarray(m.bin_upper_bound[:r], np.float64)
                          + 0.0)                     # -0.0 -> +0.0
        max_r = max(rs, default=0)
        Bp = 1 << max(int(np.ceil(np.log2(max_r + 1))), 0)
        self._Bp = Bp
        Fn = len(self.num_inner)
        if self.f32_input:
            bk = np.full((Fn, Bp), np.uint32(0xFFFFFFFF), np.uint32)
            for k, bu in enumerate(bounds):
                bk[k, :len(bu)] = _key32_host(_floor32(bu))
            self._bhi = jnp.asarray(bk)
            self._blo = None
        else:
            bh = np.full((Fn, Bp), np.uint32(0xFFFFFFFF), np.uint32)
            bl = np.full((Fn, Bp), np.uint32(0xFFFFFFFF), np.uint32)
            for k, bu in enumerate(bounds):
                h, lo = _keys64_host(bu)
                bh[k, :len(bu)] = h
                bl[k, :len(bu)] = lo
            self._bhi = jnp.asarray(bh)
            self._blo = jnp.asarray(bl)
        self._nan_bin = jnp.asarray(np.asarray(nan_bins, np.int32))

        # categorical tables (kept per-feature: lengths differ)
        self._cats = [jnp.asarray(np.asarray(m.bin_2_categorical,
                                             np.int64).astype(np.int32))
                      for m in (mappers[i] for i in self.cat_inner)]
        self._cat_nbin = [mappers[i].num_bin for i in self.cat_inner]

        # static output permutation: chunk kernel emits [numerical;
        # categorical] row blocks, take() restores mapper order
        order = np.asarray(self.num_inner + self.cat_inner, np.int64)
        self._inv_perm = jnp.asarray(np.argsort(order).astype(np.int32))
        self._chunk_fn = self._build_chunk_fn()

    # -- kernel --------------------------------------------------------------

    def _build_chunk_fn(self):
        import jax
        import jax.numpy as jnp

        Bp = self._Bp
        bhi, blo = self._bhi, self._blo
        nan_bin = self._nan_bin
        cats, cat_nbin = self._cats, self._cat_nbin
        inv_perm = self._inv_perm
        out_dtype = self.out_dtype
        f32_input = self.f32_input
        Fn = len(self.num_inner)

        def gather(b, idx):                  # b [F,Bp], idx [C,F] -> [C,F]
            return jax.vmap(lambda col, i: col[i],
                            in_axes=(0, 1), out_axes=1)(b, idx)

        def lower_bound(xh, xl):
            """Branchless count of bounds < x per (row, feature):
            uniform binary search, Bp a power of two, pad = max key."""
            pos = jnp.zeros(xh.shape, jnp.int32)
            step = Bp
            while step > 1:
                step //= 2
                idx = pos + (step - 1)
                gh = gather(bhi, idx)
                go = gh < xh
                if xl is not None:
                    gl = gather(blo, idx)
                    go = go | ((gh == xh) & (gl < xl))
                pos = jnp.where(go, pos + step, pos)
            return pos

        def key32_dev(x):
            b = jax.lax.bitcast_convert_type(x, jnp.uint32)
            neg = (b >> jnp.uint32(31)).astype(bool)
            mask = jnp.where(neg, jnp.uint32(0xFFFFFFFF),
                             jnp.uint32(0x80000000))
            return b ^ mask

        def chunk(xa, xb, nan, cat_iv):
            """One chunk -> [F, C] bins. f32 input: xa = raw f32
            [C, Fn], xb unused. f64 input: xa/xb = hi/lo key planes
            (uint32), nan = host NaN mask."""
            parts = []
            if Fn:
                if f32_input:
                    nanm = jnp.isnan(xa)
                    v = jnp.where(nanm, jnp.float32(0.0), xa) \
                        + jnp.float32(0.0)           # -0.0 -> +0.0
                    pos = lower_bound(key32_dev(v), None)
                else:
                    nanm = nan
                    pos = lower_bound(xa, xb)
                out_num = jnp.where(nanm & (nan_bin[None, :] >= 0),
                                    nan_bin[None, :], pos)
                parts.append(out_num.T)
            for k, cvals in enumerate(cats):
                iv = cat_iv[:, k]
                default = jnp.int32(cat_nbin[k] - 1)
                if cvals.shape[0]:
                    eq = iv[:, None] == cvals[None, :]
                    hit = jnp.argmax(eq, axis=1).astype(jnp.int32)
                    out_c = jnp.where(eq.any(axis=1), hit, default)
                else:
                    out_c = jnp.full(iv.shape, default, jnp.int32)
                parts.append(out_c[None, :])
            allout = (parts[0] if len(parts) == 1
                      else jnp.concatenate(parts, axis=0))
            return jnp.take(allout, inv_perm, axis=0).astype(out_dtype)

        # jit-capture: ok(Fn, f32_input, out_dtype, nan_bin, cats,
        # cat_nbin, inv_perm, key32_dev, lower_bound) —
        # per-binner jit: the captured mapper tables ARE the kernel's
        # constants, derived from THIS dataset's bin mappers and
        # cached on the binner instance (one binner per dataset,
        # asserted by create_valid's mapper-reuse contract).
        return jax.jit(chunk)

    # -- host-side chunk prep ------------------------------------------------

    def _prep_chunk(self, X: np.ndarray, pad_to: Optional[int] = None):
        """Slice + key one chunk on the host (worker-thread half of the
        double buffer). Returns the transfer tuple, tail-padded to the
        fixed chunk shape so every chunk reuses one compiled kernel —
        or to ``pad_to`` rows (the ring path, which splices the
        remaining pad tail from the device-resident slot instead of
        re-uploading it)."""
        from ..utils import faults
        if faults.active():
            faults.check("ingest.prep", context=f"{X.shape[0]} rows")
        with trace.span("ingest/prep_chunk", cat="ingest",
                        args={"rows": int(X.shape[0])}):
            return self._prep_chunk_inner(X, pad_to)

    def _prep_chunk_inner(self, X: np.ndarray,
                          pad_to: Optional[int] = None):
        C = pad_to if pad_to is not None else self.chunk_rows
        k = X.shape[0]
        pad = C - k
        Xn = X[:, self.num_cols] if len(self.num_cols) else \
            np.zeros((k, 0), X.dtype)
        if self.f32_input:
            xa = np.ascontiguousarray(Xn, np.float32)
            if pad:
                xa = np.pad(xa, ((0, pad), (0, 0)))
            xb = nan = np.zeros((0,), np.uint32)   # unused placeholders
        else:
            v = np.ascontiguousarray(Xn, np.float64)
            nanm = np.isnan(v)
            v = np.where(nanm, 0.0, v) + 0.0        # NaN->0, -0.0->+0.0
            xa, xb = _keys64_host(v)
            nan = nanm
            if pad:
                xa = np.pad(xa, ((0, pad), (0, 0)))
                xb = np.pad(xb, ((0, pad), (0, 0)))
                nan = np.pad(nan, ((0, pad), (0, 0)))
        if len(self.cat_cols):
            cat_iv = _cat_iv_host(X[:, self.cat_cols])
            if pad:
                cat_iv = np.pad(cat_iv, ((0, pad), (0, 0)),
                                constant_values=-1)
        else:
            cat_iv = np.zeros((C, 0), np.int32)
        return (xa, xb, nan, cat_iv), k

    def _submit(self, prepped, device=None, assemble=None):
        """Main-thread half: async transfer + kernel dispatch. Returns
        the [F, k] device block (tail chunks sliced to their true
        rows). ``device`` pins the transfer AND the kernel to one mesh
        device (sharded ingest); None = the default device.
        ``assemble`` (the ring path) maps the transferred arrays to
        the full-chunk tuple the kernel consumes — ONE copy of the
        transfer protocol (fault point, retry, span, h2d ledger)
        serves both paths."""
        import jax
        arrs, k = prepped
        nbytes = sum(int(a.nbytes) for a in arrs)
        from ..utils import faults, retry

        def put():
            # transient transfer failures (RESOURCE_EXHAUSTED on a busy
            # tunnel, an injected ingest.device_put fault) retry with
            # bounded backoff instead of killing the pipeline
            if faults.active():
                faults.check("ingest.device_put",
                             context=f"{nbytes} bytes")
            return jax.device_put(arrs, device)

        span_args = {"rows": int(k), "bytes": nbytes}
        if assemble is not None:
            span_args["ring"] = True
        with trace.span("ingest/chunk", cat="ingest", args=span_args):
            with timing.phase("binning/device_xfer"):
                arrs = retry.call(
                    put, what="ingest device_put",
                    policy=self.retry_policy)
            obs.counter("ingest/h2d_bytes").add(nbytes)
            obs.counter("ingest/h2d_chunks").add(1)
            obs.counter("ingest/rows_device").add(k)
            if assemble is not None:
                arrs = assemble(arrs)
            out = self._chunk_fn(*arrs)
        if k < self.chunk_rows:
            out = out[:, :k]
        return out

    # -- drivers -------------------------------------------------------------

    def bin_matrix(self, X: np.ndarray,
                   ring: Optional[ChunkRing] = None):
        """Whole in-memory matrix -> [F, N] device bins with the
        double-buffered pipeline (worker preps chunk k+1 while chunk
        k's transfer + kernel are in flight). With a ``ring``, chunk
        slots reuse the device-resident buffers of the previous
        same-geometry construction and only the bucketed live-row
        region crosses the wire (see ChunkRing)."""
        import jax.numpy as jnp
        n = X.shape[0]
        C = self.chunk_rows
        if ring is not None:
            if -(-n // C) <= ring.capacity:
                return self._bin_matrix_ringed(X, ring)
            # a matrix wider than the ring would evict every slot
            # before its next-window reuse: every get would miss while
            # every put still pins a full resident chunk — pure
            # overhead, so take the plain path instead
            log.debug("chunk ring bypassed: %d chunks exceed ring "
                      "capacity %d", -(-n // C), ring.capacity)
        starts = list(range(0, n, C))

        def thunk(r0):
            return lambda: self._prep_chunk(X[r0:min(r0 + C, n)])

        outs = [self._submit(p)
                for p in prefetch((thunk(r0) for r0 in starts),
                                  what="ingest chunk",
                                  policy=self.retry_policy)]
        bins_t = outs[0] if len(outs) == 1 else jnp.concatenate(outs, 1)
        log.debug("device ingest: %d rows x %d features in %d chunk(s) "
                  "of %d rows", n, len(self.mappers), len(outs), C)
        return bins_t

    # -- ring path ------------------------------------------------------------

    def _geom_key(self) -> tuple:
        """Chunk geometry the ring's resident buffers are only valid
        for: the raw-value planes depend on the source columns, dtype
        scheme and fixed chunk rows — NOT on the bin mappers, which is
        exactly why a fresh window's fresh mappers can bin resident
        rows bit-identically."""
        return (self.chunk_rows, self.f32_input,
                tuple(int(c) for c in self.num_cols),
                tuple(int(c) for c in self.cat_cols))

    def _ring_tail(self, idx: int, rows: int, like) -> "object":
        """Device-created pad tail for a cold slot: the host binner's
        pad constants (zeros; -1 for the categorical plane), never
        crossing the wire."""
        import jax.numpy as jnp
        fill = -1 if idx == 3 else 0
        return jnp.full((rows,) + tuple(like.shape[1:]), fill,
                        like.dtype)

    def _ring_assemble(self, up, resident, U: int):
        """Splice the uploaded [U, ...] row blocks onto each resident
        slot's pad tail -> full chunk_rows arrays (on device)."""
        import jax.numpy as jnp
        C = self.chunk_rows
        full = []
        for i, a in enumerate(up):
            if getattr(a, "ndim", 0) != 2 or a.shape[0] != U or U >= C:
                # placeholders ((0,)-shaped f32-mode planes) and
                # full-width uploads pass through
                full.append(a)
                continue
            tail = (resident[i][U:] if resident is not None
                    else self._ring_tail(i, C - U, a))
            full.append(jnp.concatenate([a, tail], axis=0))
        return tuple(full)

    def _bin_matrix_ringed(self, X: np.ndarray, ring: ChunkRing):
        import jax.numpy as jnp
        n = X.shape[0]
        C = self.chunk_rows
        geom = self._geom_key()
        plans = []                      # (slot, live rows, U, resident)
        for slot, r0 in enumerate(range(0, n, C)):
            k = min(C, n - r0)
            resident, valid = ring.get(slot, geom)
            plans.append((slot, r0, k,
                          ring_upload_rows(k, valid, C), resident))

        def thunk(p):
            slot, r0, k, U, _ = p
            return lambda: (p, self._prep_chunk(X[r0:r0 + k], pad_to=U))

        outs = []
        saved = 0
        for p, prepped in prefetch((thunk(p) for p in plans),
                                   what="ingest ring chunk",
                                   policy=self.retry_policy):
            slot, _, k, U, resident = p

            def assemble(up, slot=slot, resident=resident, U=U, k=k):
                full = self._ring_assemble(up, resident, U)
                if U < C:
                    # full-width uploads are NOT stored: pinning a
                    # whole chunk buys nothing (the next partial
                    # window's cold path makes its pad tail on device)
                    # and would force that window to re-cover the full
                    # previous valid extent
                    ring.put(slot, geom, full, valid=k)
                return full

            outs.append(self._submit(prepped, assemble=assemble))
            # bounded-cardinality: two literal names (hit/miss)
            obs.counter("ingest/ring_hits"
                        if resident is not None
                        else "ingest/ring_misses").add(1)
            # bytes the full-pad path would have shipped for the rows
            # the ring kept resident (or created on device)
            up, _k = prepped
            saved += sum((C - U) * int(a.nbytes) // max(a.shape[0], 1)
                         for a in up if getattr(a, "ndim", 0) == 2
                         and a.shape[0] == U and U < C)
        if saved:
            obs.counter("ingest/ring_saved_bytes").add(saved)
        bins_t = outs[0] if len(outs) == 1 else jnp.concatenate(outs, 1)
        log.debug("device ingest (ring): %d rows x %d features in %d "
                  "chunk(s) of %d rows", n, len(self.mappers),
                  len(outs), C)
        return bins_t

    def bin_matrix_sharded(self, X: np.ndarray, mesh):
        """Whole in-memory matrix -> ROW-SHARDED [F, N_pad] device bins
        under ``NamedSharding(mesh, P(None, AXIS))``, assembled with NO
        single-device staging: device d owns the contiguous global row
        block [d*S, (d+1)*S) (S = ceil(N/D); tail rows of the last
        shard are zero bins, the same values row padding would write),
        its chunks stream host->device pinned to d, and the chunk
        submission round-robins ACROSS devices so every chip's transfer
        + bin kernel overlap the next chip's host prep. Bit-exact with
        ``bin_matrix``: the identical compiled chunk kernel maps the
        identical row slices — only the destination device differs.

        Returns a jax.Array whose trailing ``N_pad - N`` columns are
        padding (the caller records the true row count)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..parallel.learners import AXIS

        devs = list(mesh.devices.reshape(-1))
        D = len(devs)
        n = X.shape[0]
        C = self.chunk_rows
        S = shard_width(n, D, self.hist_chunk)

        # interleaved (device, row-slice) submission order: chunk k of
        # every shard before chunk k+1 of any — the round-robin that
        # keeps all D transfer queues busy while ONE prefetch worker
        # preps ahead in the same order
        tasks = []   # (device index, start row, rows)
        max_chunks = -(-S // C)
        for k in range(max_chunks):
            for d in range(D):
                r0 = d * S + k * C
                r1 = min(d * S + S, n, r0 + C)
                if r0 < min(d * S + S, n):
                    tasks.append((d, r0, r1 - r0))

        def thunk(t):
            d, r0, rows = t
            return lambda: (d, self._prep_chunk(X[r0:r0 + rows]))

        per_dev = [[] for _ in range(D)]
        for prepped in prefetch((thunk(t) for t in tasks),
                                what="sharded ingest chunk",
                                policy=self.retry_policy):
            d, p = prepped
            per_dev[d].append(self._submit(p, device=devs[d]))

        shards = []
        for d in range(D):
            rows_d = max(min(S, n - d * S), 0)
            parts = per_dev[d]
            if rows_d < S:
                # zero-bin tail (row padding): committed to device d so
                # the assembled shard never leaves it
                parts.append(jax.device_put(
                    jnp.zeros((len(self.mappers), S - rows_d),
                              self.out_dtype), devs[d]))
            shards.append(parts[0] if len(parts) == 1
                          else jnp.concatenate(parts, axis=1))
        sharding = NamedSharding(mesh, P(None, AXIS))
        bins_t = jax.make_array_from_single_device_arrays(
            (len(self.mappers), D * S), sharding, shards)
        log.debug("sharded device ingest: %d rows x %d features over "
                  "%d device(s) (%d-row shards, %d-row chunks)",
                  n, len(self.mappers), D, S, C)
        return bins_t

    def bin_matrix_multihost(self, X_local: np.ndarray, mesh,
                             n_global: int, row_start: int):
        """Per-host half of a MULTI-PROCESS sharded ingest: this host
        streams only its own contiguous row block through the
        double-buffered pipeline onto its ADDRESSABLE devices, and the
        global [F, N_pad] bin matrix assembles across processes via
        ``make_array_from_single_device_arrays`` — no rank ever holds
        (or transfers) another rank's rows. The device->row-block map
        is IDENTICAL to ``bin_matrix_sharded``'s (global mesh position
        gd owns global rows [gd*S, (gd+1)*S)), so a single-process
        mesh and a multi-process mesh of the same size produce the
        same global layout bit-for-bit.

        ``X_local`` holds global rows [row_start, row_start + len)
        and must cover every block owned by this process's devices
        (parallel/elastic.py's loader slices exactly that).
        """
        import jax
        from ..parallel import cluster
        from ..parallel.learners import AXIS

        positions = list(mesh.devices.reshape(-1))
        D = len(positions)
        n = int(n_global)
        C = self.chunk_rows
        S = shard_width(n, D, self.hist_chunk)

        proc = jax.process_index()
        local = [(gd, dev) for gd, dev in enumerate(positions)
                 if dev.process_index == proc]
        n_local = X_local.shape[0]
        for gd, _ in local:
            lo = gd * S
            hi = min(lo + S, n)
            if lo < hi and not (row_start <= lo
                                and hi <= row_start + n_local):
                raise ValueError(
                    f"multihost ingest: rank's rows [{row_start}, "
                    f"{row_start + n_local}) do not cover device "
                    f"{gd}'s block [{lo}, {hi}) — slice per-host data "
                    f"with elastic.host_row_block so host and device "
                    f"blocks line up")

        # interleaved (device, chunk) submission across the LOCAL
        # devices — the same round-robin overlap as the single-process
        # sharded path, per host
        tasks = []     # (local index, global row start, rows)
        max_chunks = -(-S // C)
        for k in range(max_chunks):
            for li, (gd, _) in enumerate(local):
                r0 = gd * S + k * C
                r1 = min(gd * S + S, n, r0 + C)
                if r0 < min(gd * S + S, n):
                    tasks.append((li, r0, r1 - r0))

        def thunk(t):
            li, r0, rows = t
            lo = r0 - row_start
            return lambda: (li, self._prep_chunk(
                X_local[lo:lo + rows]))

        per_dev = [[] for _ in local]
        for prepped in prefetch((thunk(t) for t in tasks),
                                what="multihost ingest chunk",
                                policy=self.retry_policy):
            li, p = prepped
            per_dev[li].append(self._submit(p, device=local[li][1]))

        import jax.numpy as jnp
        shards = []
        for li, (gd, dev) in enumerate(local):
            rows_d = max(min(S, n - gd * S), 0)
            parts = per_dev[li]
            if rows_d < S:
                parts.append(jax.device_put(
                    jnp.zeros((len(self.mappers), S - rows_d),
                              self.out_dtype), dev))
            shards.append(parts[0] if len(parts) == 1
                          else jnp.concatenate(parts, axis=1))
        bins_t = cluster.local_shards_to_global(
            shards, (len(self.mappers), D * S), mesh, None, AXIS)
        obs.counter("ingest/rows_local_host").add(
            sum(min(S, max(n - gd * S, 0)) for gd, _ in local))
        log.info("multihost device ingest: rank %d/%d binned %d of %d "
                 "global rows onto %d local device(s) (%d-row shards)",
                 cluster.rank(), cluster.world(),
                 sum(min(S, max(n - gd * S, 0)) for gd, _ in local),
                 n, len(local), S)
        return bins_t

    def start_stream(self) -> "IngestStream":
        return IngestStream(self)

    def start_sharded_stream(self, mesh, n_global: int
                             ) -> "ShardedIngestStream":
        return ShardedIngestStream(self, mesh, n_global)


# -- CSR-native sparse ingest -------------------------------------------------

# sparse chunks carry a VARIABLE entry count: pad each plane set to a
# power-of-two bucket so the compiled chunk kernel is shared across
# chunks/windows (the step-cache shape-bucketing discipline); sentinel
# entries carry feature index F and are dropped by the device scatter
_SPARSE_ENTRY_FLOOR = 2048


def sparse_entry_bucket(e: int) -> int:
    """Padded entry-plane length for ``e`` explicit entries — the
    shared pow2 shape-taper, floored so tiny chunks share one compiled
    kernel."""
    from ..ops.step_cache import pow2_bucket
    return pow2_bucket(e, _SPARSE_ENTRY_FLOOR)


class SparseDeviceBinner(DeviceBinner):
    """Device-side binning of CSR chunks riding the same
    double-buffered prefetch pipeline as the dense ``DeviceBinner``.

    The host half (worker thread) slices a row-chunk of the CSR matrix
    and keys its explicit VALUES exactly like the dense prep — the
    sortable-integer f64 hi/lo planes of the module docstring — with
    the entry COLUMN/ROW indices as two more planes on the transfer
    thunk. The device half runs the same branchless lower-bound search
    PER ENTRY (bounds row gathered by each entry's feature) and
    scatters the resulting bin codes over a zero-bin-filled ``[F, C]``
    block — the dense feature-major chunk layout, assembled without any
    host [N, F] matrix at any width. Bit-exact vs the host
    ``value_to_bin`` by the same argument as the dense kernel: the key
    comparisons are identical, and implicit cells take the
    host-computed ``zero_bins`` constants.

    Categorical entries are coded on the host in the prep thunk (few,
    cheap — the dense path already host-truncates categoricals).

    ``bin_matrix_sparse`` optionally also returns the zero-suppressed
    (code, feature, row) coordinate planes — device-resident, already
    binned — which feed the sparse histogram tier
    (ops/hist_wave.py ``wave_histogram_sparse``)."""

    def __init__(self, mappers: List[BinMapper],
                 used_feature_map: np.ndarray, config) -> None:
        super().__init__(mappers, used_feature_map, config, np.float64)
        import jax.numpy as jnp
        from .sparse import zero_bins
        self._zb_dev = jnp.asarray(zero_bins(mappers))
        # real column -> (inner feature, numerical-bounds row) lookups,
        # built lazily at the matrix width (entries on TRIVIAL columns
        # must be dropped, and those columns sit outside used_feature_map)
        self._lut_nf = -1
        self._lut_inner = None
        self._lut_numpos = None
        self._inner_is_cat = np.zeros(len(mappers), bool)
        self._inner_is_cat[self.cat_inner] = True
        self._sparse_fn = self._build_sparse_chunk_fn()

    def _lut(self, nf: int):
        if self._lut_nf != nf:
            used = np.asarray(
                [int(c) for c in np.concatenate(
                    [self.num_cols, self.cat_cols])] or [], np.int64)
            inner_of = np.concatenate(
                [self.num_inner, self.cat_inner]).astype(np.int64) \
                if len(used) else np.zeros(0, np.int64)
            real2inner = np.full(nf, -1, np.int64)
            real2inner[used] = inner_of
            inner2numpos = np.full(len(self.mappers), 0, np.int64)
            inner2numpos[self.num_inner] = np.arange(
                len(self.num_inner))
            self._lut_nf = nf
            self._lut_inner = real2inner
            self._lut_numpos = inner2numpos
        return self._lut_inner, self._lut_numpos

    # -- device kernel -------------------------------------------------------

    def _build_sparse_chunk_fn(self):
        import jax
        import jax.numpy as jnp

        Bp = self._Bp
        bhi, blo = self._bhi, self._blo
        nan_bin = self._nan_bin
        zb = self._zb_dev
        out_dtype = self.out_dtype
        C = self.chunk_rows
        F = len(self.mappers)
        Fn = len(self.num_inner)

        def lower_bound_entries(xh, xl, fb):
            """Count of bounds < x per entry, bounds row gathered by
            the entry's feature — the dense kernel's uniform binary
            search, per entry instead of per (row, feature)."""
            pos = jnp.zeros(xh.shape, jnp.int32)
            step = Bp
            while step > 1:
                step //= 2
                idx = pos + (step - 1)
                gh = bhi[fb, idx]
                go = gh < xh
                gl = blo[fb, idx]
                go = go | ((gh == xh) & (gl < xl))
                pos = jnp.where(go, pos + step, pos)
            return pos

        def chunk(r0, xa, xb, nan, nb, ni, nr, ci, cr, cc):
            """One CSR chunk -> ([F, C] bins, per-entry coords).

            xa/xb: f64 hi/lo key planes of the numerical entry values;
            nan: host NaN mask; nb: bounds-row index; ni/nr: inner
            feature + local row per numerical entry; ci/cr/cc: inner
            feature / local row / host-coded bin per categorical
            entry. Sentinel (pad) entries carry feature F — out of
            bounds for every scatter, dropped by mode="drop"."""
            out = jnp.broadcast_to(
                zb.astype(out_dtype)[:, None], (F, C))
            if Fn and xa.shape[0]:
                pos = lower_bound_entries(xa, xb, nb)
                code_n = jnp.where(nan & (nan_bin[nb] >= 0),
                                   nan_bin[nb], pos)
            else:
                code_n = jnp.zeros((0,), jnp.int32)
            out = out.at[ni, nr].set(code_n.astype(out_dtype),
                                     mode="drop")
            if cc.shape[0]:
                out = out.at[ci, cr].set(cc.astype(out_dtype),
                                         mode="drop")
            codes = jnp.concatenate([code_n, cc]).astype(jnp.int32)
            feat = jnp.concatenate([ni, ci])
            rows = jnp.concatenate([nr, cr]) + r0
            return out, codes, feat, rows

        # jit-capture: ok(C, zb, nan_bin, out_dtype,
        # lower_bound_entries) — per-binner jit (see the dense
        # DeviceBinner note above): zero-bin/nan tables are this
        # dataset's mapper constants, cached on the binner instance.
        return jax.jit(chunk)

    # -- host-side chunk prep ------------------------------------------------

    def _prep_sparse_chunk(self, sm, r0: int, r1: int):
        from ..utils import faults
        if faults.active():
            faults.check("ingest.prep", context=f"{r1 - r0} rows")
        with trace.span("ingest/prep_chunk", cat="ingest",
                        args={"rows": int(r1 - r0), "sparse": True}):
            return self._prep_sparse_chunk_inner(sm, r0, r1)

    def _prep_sparse_chunk_inner(self, sm, r0: int, r1: int):
        sub = sm.row_slice(r0, r1)
        real2inner, inner2numpos = self._lut(sm.shape[1])
        inner = real2inner[sub.cols]
        lrows = sub.rows().astype(np.int32)
        F = len(self.mappers)
        kept = inner >= 0
        is_cat = np.zeros(len(inner), bool)
        is_cat[kept] = self._inner_is_cat[inner[kept]]
        numm = kept & ~is_cat
        catm = kept & is_cat

        # numerical planes: keyed values + indices (NaN -> key of +0.0
        # with the mask riding separately, -0.0 normalized — the dense
        # prep's exact recipe)
        v = sub.data[numm]
        nanm = np.isnan(v)
        v = np.where(nanm, 0.0, v) + 0.0
        xa, xb = _keys64_host(v)
        nb = inner2numpos[inner[numm]].astype(np.int32)
        ni = inner[numm].astype(np.int32)
        nr = lrows[numm]
        if len(self.num_inner):
            En = sparse_entry_bucket(len(v))
            pad = En - len(v)
            if pad:
                xa = np.pad(xa, (0, pad))
                xb = np.pad(xb, (0, pad))
                nanm = np.pad(nanm, (0, pad))
                nb = np.pad(nb, (0, pad))
                ni = np.pad(ni, (0, pad), constant_values=F)
                nr = np.pad(nr, (0, pad))

        # categorical planes: host-coded (few columns, cheap — the
        # dense path host-truncates categoricals the same way)
        if len(self.cat_inner):
            cis, crs, ccs = [], [], []
            for i in self.cat_inner:
                m2 = catm & (inner == i)
                if not m2.any():
                    continue
                ccs.append(np.asarray(
                    self.mappers[i].value_to_bin(sub.data[m2]),
                    np.int32))
                cis.append(np.full(int(m2.sum()), i, np.int32))
                crs.append(lrows[m2])
            ci = (np.concatenate(cis) if cis else np.zeros(0, np.int32))
            cr = (np.concatenate(crs) if crs else np.zeros(0, np.int32))
            cc = (np.concatenate(ccs) if ccs else np.zeros(0, np.int32))
            Ec = sparse_entry_bucket(len(cc))
            pad = Ec - len(cc)
            ci = np.pad(ci, (0, pad), constant_values=F)
            cr = np.pad(cr, (0, pad))
            cc = np.pad(cc, (0, pad))
        else:
            ci = cr = cc = np.zeros(0, np.int32)
        return (r0, (xa, xb, nanm, nb, ni, nr, ci, cr, cc),
                r1 - r0)

    # -- driver --------------------------------------------------------------

    def _submit_sparse(self, prepped):
        import jax
        import jax.numpy as jnp
        r0, arrs, k = prepped
        nbytes = sum(int(a.nbytes) for a in arrs)
        from ..utils import faults, retry

        def put():
            if faults.active():
                faults.check("ingest.device_put",
                             context=f"{nbytes} bytes")
            return jax.device_put(arrs)

        with trace.span("ingest/chunk", cat="ingest",
                        args={"rows": int(k), "bytes": nbytes,
                              "sparse": True}):
            with timing.phase("binning/device_xfer"):
                arrs = retry.call(put, what="sparse ingest device_put",
                                  policy=self.retry_policy)
            obs.counter("ingest/h2d_bytes").add(nbytes)
            obs.counter("ingest/h2d_chunks").add(1)
            obs.counter("ingest/rows_device").add(k)
            out, codes, feat, rows = self._sparse_fn(jnp.int32(r0),
                                                     *arrs)
        if k < self.chunk_rows:
            out = out[:, :k]
        return out, (codes, feat, rows)

    def bin_matrix_sparse(self, sm, want_coords: bool = False):
        """CSR matrix -> ([F, N] device bins, coords or None) with the
        double-buffered pipeline: the worker keys chunk k+1's entry
        planes while chunk k's transfer + kernel are in flight.
        ``coords`` = (codes, feat, rows) device planes over every
        chunk's entries — sentinel (pad) entries carry feature F, which
        every downstream scatter drops."""
        import jax.numpy as jnp
        n = sm.shape[0]
        C = self.chunk_rows
        starts = list(range(0, n, C))

        def thunk(r0):
            return lambda: self._prep_sparse_chunk(
                sm, r0, min(r0 + C, n))

        outs, codes, feats, rows = [], [], [], []
        for p in prefetch((thunk(r0) for r0 in starts),
                          what="sparse ingest chunk",
                          policy=self.retry_policy):
            block, co = self._submit_sparse(p)
            outs.append(block)
            if want_coords:
                codes.append(co[0])
                feats.append(co[1])
                rows.append(co[2])
        bins_t = outs[0] if len(outs) == 1 else jnp.concatenate(outs, 1)
        coords = None
        if want_coords:
            coords = (jnp.concatenate(codes), jnp.concatenate(feats),
                      jnp.concatenate(rows))
        log.debug("sparse device ingest: %d rows x %d features "
                  "(nnz=%d) in %d chunk(s) of %d rows", n,
                  len(self.mappers), sm.nnz, len(outs), C)
        return bins_t, coords


class IngestStream:
    """Feed-driven variant for streaming loaders (two-round text
    loading): rows arrive in parser-sized blocks, are repacked to the
    binner's chunk granularity and dispatched asynchronously — the
    caller's parsing of the next block IS the host half of the double
    buffer."""

    def __init__(self, binner: DeviceBinner):
        self._b = binner
        self._pend: List[np.ndarray] = []
        self._pend_rows = 0
        self._outs: List = []
        self._rows = 0

    def feed(self, X: np.ndarray) -> None:
        C = self._b.chunk_rows
        self._pend.append(np.asarray(X))
        self._pend_rows += X.shape[0]
        self._rows += X.shape[0]
        while self._pend_rows >= C:
            block = (self._pend[0] if len(self._pend) == 1
                     else np.concatenate(self._pend, axis=0))
            self._outs.append(self._b._submit(
                self._b._prep_chunk(block[:C])))
            rest = block[C:]
            self._pend = [rest] if rest.shape[0] else []
            self._pend_rows = rest.shape[0]

    def finish(self):
        """-> [F, N] device bins over every fed row."""
        import jax.numpy as jnp
        if self._pend_rows:
            block = (self._pend[0] if len(self._pend) == 1
                     else np.concatenate(self._pend, axis=0))
            self._outs.append(self._b._submit(self._b._prep_chunk(block)))
            self._pend, self._pend_rows = [], 0
        if not self._outs:
            return jnp.zeros((len(self._b.mappers), 0),
                             self._b.out_dtype)
        return (self._outs[0] if len(self._outs) == 1
                else jnp.concatenate(self._outs, axis=1))


class ShardedIngestStream:
    """Feed-driven variant of ``bin_matrix_sharded`` /
    ``bin_matrix_multihost`` for the out-of-core two-round loader:
    global rows arrive IN FILE ORDER in parser-sized blocks, and mesh
    position gd owns the contiguous global row block [gd*S, (gd+1)*S)
    exactly as the in-memory sharded drivers lay it out — so the stream
    slices at shard/chunk boundaries and dispatches each completed
    chunk pinned to the owning device while the caller parses the next
    text block. On a multi-process mesh every rank parses the whole
    file but TRANSFERS only the rows its addressable devices own;
    ``finish()`` assembles the global [F, N_pad] array with the same
    cross-process assembly as ``bin_matrix_multihost``. Bit-exact vs
    the in-memory drivers: identical compiled chunk kernel, identical
    row->device map (chunk k of shard gd covers global rows
    [gd*S + k*C, min(gd*S + (k+1)*C, (gd+1)*S, n)))."""

    def __init__(self, binner: DeviceBinner, mesh, n_global: int):
        import jax
        self._b = binner
        self._mesh = mesh
        self._n = int(n_global)
        self._positions = list(mesh.devices.reshape(-1))
        self._S = shard_width(self._n, len(self._positions),
                              binner.hist_chunk)
        proc = jax.process_index()
        self._local = {gd: dev
                       for gd, dev in enumerate(self._positions)
                       if dev.process_index == proc}
        self._multiproc = any(d.process_index != proc
                              for d in self._positions)
        self._cursor = 0                # global row index of _pend[0]
        self._pend: List[np.ndarray] = []
        self._pend_rows = 0
        self._outs = {gd: [] for gd in self._local}
        self._rows_local = 0

    def _boundary(self):
        """(owning shard, next dispatch boundary) for the cursor: the
        end of the current chunk, clipped to the shard end and n."""
        S, C, n = self._S, self._b.chunk_rows, self._n
        gd = self._cursor // S
        off = self._cursor - gd * S
        return gd, min(gd * S + (off // C + 1) * C, (gd + 1) * S, n)

    def feed(self, X: np.ndarray) -> None:
        self._pend.append(np.asarray(X))
        self._pend_rows += X.shape[0]
        while self._pend_rows and self._cursor < self._n:
            gd, bnd = self._boundary()
            need = bnd - self._cursor
            if self._pend_rows < need:
                break
            self._emit(gd, need)

    def _emit(self, gd: int, rows: int) -> None:
        block = (self._pend[0] if len(self._pend) == 1
                 else np.concatenate(self._pend, axis=0))
        take, rest = block[:rows], block[rows:]
        self._pend = [rest] if rest.shape[0] else []
        self._pend_rows = int(rest.shape[0])
        self._cursor += rows
        if gd in self._local:
            self._outs[gd].append(self._b._submit(
                self._b._prep_chunk(take), device=self._local[gd]))
            self._rows_local += rows

    def finish(self):
        """-> row-sharded [F, N_pad] device bins over every fed row
        (trailing ``N_pad - n`` columns are zero-bin padding)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..parallel.learners import AXIS
        while self._pend_rows and self._cursor < self._n:
            gd, bnd = self._boundary()
            self._emit(gd, min(bnd - self._cursor,
                                   self._pend_rows))
        n, S = self._n, self._S
        D = len(self._positions)
        F = len(self._b.mappers)
        shards = []
        for gd, dev in self._local.items():
            rows_d = max(min(S, n - gd * S), 0)
            parts = self._outs[gd]
            if rows_d < S:
                # zero-bin tail (row padding): committed to device gd
                # so the assembled shard never leaves it
                parts.append(jax.device_put(
                    jnp.zeros((F, S - rows_d), self._b.out_dtype),
                    dev))
            shards.append(parts[0] if len(parts) == 1
                          else jnp.concatenate(parts, axis=1))
        if self._multiproc:
            from ..parallel import cluster
            obs.counter("ingest/rows_local_host").add(self._rows_local)
            return cluster.local_shards_to_global(
                shards, (F, D * S), self._mesh, None, AXIS)
        sharding = NamedSharding(self._mesh, P(None, AXIS))
        return jax.make_array_from_single_device_arrays(
            (F, D * S), sharding, shards)
