"""Virtual file abstraction: scheme-dispatched readers/writers.

TPU-native counterpart of the reference's VirtualFileReader/Writer +
optional HDFS backend (reference: include/LightGBM/utils/file_io.h:1-74,
src/io/file_io.cpp:54-120 — a vtable over local stdio and libhdfs).
Here the same seam is a scheme registry over Python file objects:
local paths open directly; ``hdfs://`` routes through pyarrow's
HadoopFileSystem when that optional dependency exists (this image ships
without it, so the backend is gated with an actionable error, matching
the reference's USE_HDFS build flag being off by default).

Register new schemes with ``register_scheme("s3", opener)`` where
``opener(path, mode)`` returns a file object.
"""
from __future__ import annotations

from typing import Callable, Dict

from ..utils import log

_SCHEMES: Dict[str, Callable] = {}


def register_scheme(scheme: str, opener: Callable) -> None:
    """opener(path: str, mode: str) -> file object."""
    _SCHEMES[scheme] = opener


def _split_scheme(path: str) -> str:
    i = path.find("://")
    if i <= 0:
        return ""
    head = path[:i]
    # windows drive letters are not schemes
    return head if len(head) > 1 else ""


def _hdfs_open(path: str, mode: str):
    try:
        from pyarrow import fs as pafs
    except ImportError:
        log.fatal(
            "hdfs:// paths need the optional pyarrow dependency "
            "(the reference gates its HDFS backend behind USE_HDFS "
            "the same way, file_io.cpp:54)")
    hdfs = pafs.HadoopFileSystem.from_uri(path)
    inner = path.split("://", 1)[1]
    inner = "/" + inner.split("/", 1)[1] if "/" in inner else "/"
    if "r" in mode:
        f = hdfs.open_input_stream(inner)
    else:
        f = hdfs.open_output_stream(inner)
    if "b" not in mode:
        import io
        return io.TextIOWrapper(f)
    return f


register_scheme("hdfs", _hdfs_open)


def open_file(path: str, mode: str = "r"):
    """Open ``path`` through the scheme registry (local files by
    default) — the VirtualFileReader/Writer::Make dispatch."""
    scheme = _split_scheme(path)
    if scheme in _SCHEMES:
        return _SCHEMES[scheme](path, mode)
    if scheme and scheme not in ("file",):
        log.fatal(f"Unknown file scheme {scheme!r} for {path}; "
                  "register one with lightgbm_tpu.io.file_io."
                  "register_scheme")
    if scheme == "file":
        path = path.split("://", 1)[1]
    return open(path, mode)


def exists(path: str) -> bool:
    """VirtualFileWriter::Exists."""
    scheme = _split_scheme(path)
    if not scheme or scheme == "file":
        import os
        return os.path.exists(path.split("://", 1)[-1])
    try:
        with open_file(path, "rb"):
            return True
    except Exception:
        return False
