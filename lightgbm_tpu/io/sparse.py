"""CSR-native sparse input: O(nnz) representation, sampling and binning.

The engine's HBM layout is dense by design (io/dataset.py), but the
HOST does not have to pay for that: a CTR/ranking matrix at 1% density
costs 800x its nnz when densified to the ``[N, F]`` float64 the old
``capi._csr_to_dense`` built (the 4 GiB memory-CLIFF warning). This
module keeps sparse input in CSR end to end on the host —

- ``SparseMatrix``: values / column indices / row offsets, the
  representation ``capi.LGBM_DatasetCreateFromCSR/CSC`` and
  ``basic.py``'s scipy detection now hand to ``TpuDataset``;
- ``find_column_mappers_sparse``: BinMapper construction sampling
  straight from CSR — the SAME rng draw, sample budget and
  ``min_data_in_leaf`` filter scaling as the dense
  ``find_column_mappers`` (io/dataset.py), and the same implied-zeros
  contract (``BinMapper.find_bin`` counts ``total - len(values)``
  zeros), so the mappers are bit-identical to the densified path's;
- ``bin_entries`` / ``host_bins_from_sparse``: O(nnz) binning of the
  explicit entries (``value_to_bin`` per entry; implicit cells take
  ``zero_bins`` = ``value_to_bin(0.0)`` per feature — the numerical
  default bin, or the bin category 0 maps to for categoricals), giving
  a bin matrix cell-for-cell equal to ``TpuDataset.bin_rows`` on the
  densified input;
- the route decision (``route_sparse``) and the densify cliff warning
  (``warn_dense_cliff``), which now fires ONLY on the explicit dense
  fallback paths.

The streamed device half (binning CSR chunks on device, assembling the
``[F, N]`` matrix by scatter) lives in io/ingest.py
``SparseDeviceBinner``; the sparse histogram kernel tier the
coordinates feed is ops/hist_wave.py ``wave_histogram_sparse``.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..utils import log
from .binning import BinMapper, BinType

# the old capi densify warning threshold: a dense float64 [N, F] above
# this many GiB is the memory cliff the sparse route exists to avoid
DENSE_CLIFF_GIB = 4.0

# chunked sparse predict (bounded densify: the predict kernels are
# row-independent, so chunking is bit-exact): a row cap AND a dense
# float64 byte budget — a 131k-column hashed-CTR matrix must not
# densify gigabytes per chunk just because its row count is small
PREDICT_CHUNK_ROWS = 65536
PREDICT_CHUNK_BYTES = 256 << 20


def predict_chunk_rows(num_cols: int) -> int:
    """Rows per chunked-predict densify block: min(row cap, rows that
    keep one dense float64 block under PREDICT_CHUNK_BYTES)."""
    return max(1, min(PREDICT_CHUNK_ROWS,
                      PREDICT_CHUNK_BYTES // (8 * max(num_cols, 1))))


def warn_dense_cliff(num_row: int, num_col: int, nnz: int,
                     what: str = "densifying") -> None:
    """The >4 GiB densify cliff warning, shared by every dense
    fallback (capi ``_csr_to_dense`` AND ``_csc_to_dense``, and the
    above-threshold route in io/dataset.py) — one guarded helper so the
    CSC path can no longer silently lack it."""
    dense_gb = num_row * num_col * 8 / 2 ** 30
    if dense_gb > DENSE_CLIFF_GIB:
        log.warning(
            "%s %dx%d sparse input to %.1f GiB (nnz=%d, density "
            "%.4f): consider is_enable_sparse=true with a lower "
            "sparse_threshold (CSR-native route), enable_bundle=true "
            "(EFB) or fewer columns",
            what, num_row, num_col, dense_gb, nnz,
            nnz / max(num_row * num_col, 1))


class SparseMatrix:
    """Row-compressed (CSR) float64 matrix: ``data``/``cols`` per
    explicit entry, ``indptr`` row offsets, ``shape`` = (N, F).

    Entries are canonical: at most one per (row, col), rows in
    ascending order (columns within a row need not be sorted). Values
    are float64 — the dtype every dense ingest path normalizes to."""

    __slots__ = ("data", "cols", "indptr", "shape")

    def __init__(self, data: np.ndarray, cols: np.ndarray,
                 indptr: np.ndarray, shape: Tuple[int, int]):
        self.data = np.asarray(data, np.float64).reshape(-1)
        self.cols = np.asarray(cols, np.int64).reshape(-1)
        self.indptr = np.asarray(indptr, np.int64).reshape(-1)
        self.shape = (int(shape[0]), int(shape[1]))
        if len(self.indptr) != self.shape[0] + 1:
            raise ValueError(
                f"indptr has {len(self.indptr)} entries for "
                f"{self.shape[0]} rows")
        if self.indptr[-1] != len(self.data):
            raise ValueError("indptr[-1] != nnz")

    # -- construction --------------------------------------------------------

    @classmethod
    def from_csr(cls, indptr, indices, data, num_col: int
                 ) -> "SparseMatrix":
        """From raw CSR planes (the c_api CSR argument shape). A
        duplicate (row, col) keeps the LAST occurrence — the same
        last-write-wins the old ``_csr_to_dense`` assignment had."""
        indptr = np.asarray(indptr, np.int64).reshape(-1)
        cols = np.asarray(indices, np.int64).reshape(-1)
        data = np.asarray(data, np.float64).reshape(-1)
        n = len(indptr) - 1
        nnz = int(indptr[-1])
        cols, data = cols[:nnz], data[:nnz]
        sm = cls(data, cols, indptr, (n, int(num_col)))
        return sm._dedupe_last_wins()

    @classmethod
    def from_csc(cls, col_ptr, indices, data, num_row: int,
                 num_col: int) -> "SparseMatrix":
        """From raw CSC planes — O(nnz log nnz) transposition to CSR
        (a stable counting order would do, but the sort is simpler and
        nnz is small by definition on this route)."""
        col_ptr = np.asarray(col_ptr, np.int64).reshape(-1)
        rows = np.asarray(indices, np.int64).reshape(-1)
        data = np.asarray(data, np.float64).reshape(-1)
        nnz = int(col_ptr[-1])
        rows, data = rows[:nnz], data[:nnz]
        cols = np.repeat(np.arange(int(num_col), dtype=np.int64),
                         np.diff(col_ptr))
        order = np.argsort(rows, kind="stable")
        rows, cols, data = rows[order], cols[order], data[order]
        indptr = np.concatenate(
            [[0], np.cumsum(np.bincount(rows, minlength=int(num_row)))])
        sm = cls(data, cols, indptr.astype(np.int64),
                 (int(num_row), int(num_col)))
        return sm._dedupe_last_wins()

    @classmethod
    def from_scipy(cls, m) -> "SparseMatrix":
        """From any scipy.sparse matrix (CSC/COO/... -> CSR)."""
        csr = m.tocsr()
        if not getattr(csr, "has_canonical_format", True):
            csr = csr.copy()            # never mutate the caller's
            csr.sum_duplicates()        # scipy-canonical: sums dups
        return cls(np.asarray(csr.data, np.float64),
                   np.asarray(csr.indices, np.int64),
                   np.asarray(csr.indptr, np.int64),
                   (int(csr.shape[0]), int(csr.shape[1])))

    def _dedupe_last_wins(self) -> "SparseMatrix":
        """Drop duplicate (row, col) entries keeping the LAST (matching
        the dense-assignment semantics of the old densify route); no-op
        (no copy) when entries are already unique."""
        key = self.rows() * self.shape[1] + self.cols
        uniq = np.unique(key)
        if len(uniq) == len(key):
            return self
        # last occurrence wins: reverse, keep first-of-reversed
        rev = key[::-1]
        _, first_rev = np.unique(rev, return_index=True)
        keep = np.sort(len(key) - 1 - first_rev)
        rows = self.rows()[keep]
        indptr = np.concatenate(
            [[0], np.cumsum(np.bincount(rows,
                                        minlength=self.shape[0]))])
        return SparseMatrix(self.data[keep], self.cols[keep],
                            indptr.astype(np.int64), self.shape)

    # -- views ---------------------------------------------------------------

    @property
    def nnz(self) -> int:
        return int(len(self.data))

    @property
    def density(self) -> float:
        n, f = self.shape
        return self.nnz / max(n * f, 1)

    def rows(self) -> np.ndarray:
        """Per-entry row index [nnz] (expanded from indptr)."""
        return np.repeat(np.arange(self.shape[0], dtype=np.int64),
                         np.diff(self.indptr))

    def row_slice(self, r0: int, r1: int) -> "SparseMatrix":
        """Rows [r0, r1) as a CSR view over the same entry arrays."""
        e0, e1 = int(self.indptr[r0]), int(self.indptr[r1])
        return SparseMatrix(self.data[e0:e1], self.cols[e0:e1],
                            self.indptr[r0:r1 + 1] - e0,
                            (r1 - r0, self.shape[1]))

    def take_rows(self, idx) -> "SparseMatrix":
        """Row subset (fancy indexing) in O(nnz taken) — vectorized
        ragged-slice gather (a python loop over a 200k-row mapper
        sample would dominate construction)."""
        idx = np.asarray(idx, np.int64).reshape(-1)
        counts = np.diff(self.indptr)[idx]
        starts = self.indptr[idx]
        indptr = np.concatenate([[0], np.cumsum(counts)])
        total = int(indptr[-1])
        if total:
            take = (np.repeat(starts - indptr[:-1], counts)
                    + np.arange(total, dtype=np.int64))
        else:
            take = np.zeros(0, np.int64)
        return SparseMatrix(self.data[take], self.cols[take],
                            indptr.astype(np.int64),
                            (len(idx), self.shape[1]))

    def __getitem__(self, idx) -> "SparseMatrix":
        return self.take_rows(idx)

    def to_dense(self, warn: bool = False) -> np.ndarray:
        """Materialize the dense [N, F] float64 matrix (the explicit
        dense fallback; ``warn`` adds the cliff warning)."""
        n, f = self.shape
        if warn:
            warn_dense_cliff(n, f, self.nnz)
        X = np.zeros((n, f), np.float64)
        X[self.rows(), self.cols] = self.data
        return X

    def to_dense_rows(self, r0: int, r1: int) -> np.ndarray:
        """Dense float64 block of rows [r0, r1) — bounded densify for
        chunked prediction."""
        return self.row_slice(r0, r1).to_dense()


# ---------------------------------------------------------------------------
# Route decision
# ---------------------------------------------------------------------------

def route_sparse(config, sm: SparseMatrix) -> bool:
    """True when sparse input should stay CSR-native: the reference's
    ``is_enable_sparse`` gate plus its ``sparse_threshold`` rule lifted
    from per-feature to the whole matrix — the implicit/default
    fraction (1 - density) must reach the threshold, else the matrix is
    dense-ish and the densified path is the faster layout."""
    if not getattr(config, "is_enable_sparse", True):
        return False
    return (1.0 - sm.density) >= float(
        getattr(config, "sparse_threshold", 0.8))


def want_coords(config, density: float) -> bool:
    """Whether dataset construction should retain the zero-suppressed
    (code, feature, row) coordinates for the sparse histogram tier —
    the tier's own gate (ops/autotune.py ``tune_hist_tier``) decides
    per booster, but coordinates must be captured at ingest time.
    Mirrors the tier rule so a dataset the auto rule is guaranteed to
    reject never pins dead coordinate planes in device memory:
    tpu_sparse=1 forces, -1 auto needs quantized histograms (where the
    tier is bit-exact) AND density under the tier's ceiling."""
    t = int(getattr(config, "tpu_sparse", -1))
    if t == 0:
        return False
    if t >= 1:
        return True
    if not getattr(config, "tpu_quantized_hist", False):
        return False
    from ..ops.autotune import SPARSE_TIER_MAX_DENSITY
    return float(density) <= SPARSE_TIER_MAX_DENSITY


# ---------------------------------------------------------------------------
# Delta-encoded coordinate transport (config.tpu_psum_wire)
# ---------------------------------------------------------------------------

def delta_pack_plane(arr) -> Optional[Tuple[int, np.ndarray]]:
    """Pack an int coordinate plane for the host->device wire as
    ``(base, int16 deltas)`` — half the transfer bytes of the int32
    plane. The planes are feature-grouped and row-sorted within each
    feature (``_entries_by_column``), so adjacent deltas are tiny for
    the row/feat planes and bin-bounded (|d| <= max_bin) for the code
    plane; reconstruction is ``base + cumsum(deltas)`` in int32 on
    device — exact integer arithmetic, so the rebuilt plane is
    BIT-identical to the direct upload. Returns None (the refusal
    path) when any adjacent delta falls outside int16 — the caller
    then uploads the plane directly."""
    a = np.asarray(arr, np.int64).ravel()
    if a.size < 2:
        return None
    d = np.diff(a)
    if d.max(initial=0) > 32767 or d.min(initial=0) < -32768:
        return None
    out = np.zeros(a.size, np.int16)
    out[1:] = d.astype(np.int16)
    return int(a[0]), out


# ---------------------------------------------------------------------------
# Bin-mapper construction from CSR
# ---------------------------------------------------------------------------

def _entries_by_column(sm: SparseMatrix, nf: int):
    """(cols_sorted, vals_sorted, starts, ends): explicit entries
    grouped per column (stable by row within each column)."""
    order = np.argsort(sm.cols, kind="stable")
    cols = sm.cols[order]
    bounds = np.searchsorted(cols, np.arange(nf + 1))
    return cols, sm.data[order], order, bounds


def find_column_mappers_sparse(sm: SparseMatrix, config,
                               categorical: Sequence[int] = (),
                               total_rows: Optional[int] = None
                               ) -> List[BinMapper]:
    """``find_column_mappers`` (io/dataset.py) sampling from CSR.

    Bit-identical mappers to the densified path: the SAME
    ``rng(data_random_seed)`` row draw, the same per-column nonzero
    filter (|v| > 1e-35 or NaN — explicit zeros are implied zeros,
    exactly as the dense column scan treats them), and the same
    ``total_sample_cnt`` denominator, so ``BinMapper.find_bin`` sees
    the identical (values, implied-zero count) inputs. ``find_bin``
    sorts its values, so per-column multiset equality suffices."""
    n, nf = sm.shape
    cfg = config
    total = n if total_rows is None else max(int(total_rows), 1)
    budget = cfg.bin_construct_sample_cnt
    if total > n > 0:
        budget = max(budget * n // total, 1)
    sample_cnt = min(budget, n)
    rng = np.random.default_rng(cfg.data_random_seed)
    if sample_cnt < n:
        idx = np.sort(rng.choice(n, sample_cnt, replace=False))
        sample = sm.take_rows(idx)
    else:
        sample = sm
    snum = sample.shape[0]
    filter_cnt = 0
    if cfg.min_data_in_leaf > 0 and total > 0:
        filter_cnt = max(int(cfg.min_data_in_leaf * snum / total), 1)
    cats = set(categorical)
    _, vals, _, bounds = _entries_by_column(sample, nf)
    keep = (np.abs(vals) > 1e-35) | np.isnan(vals)
    mappers: List[BinMapper] = []
    for j in range(nf):
        sl = slice(bounds[j], bounds[j + 1])
        nonzero = vals[sl][keep[sl]]
        m = BinMapper()
        bt = (BinType.CATEGORICAL if j in cats else BinType.NUMERICAL)
        m.find_bin(nonzero, snum, cfg.max_bin, cfg.min_data_in_bin,
                   filter_cnt, bt, cfg.use_missing, cfg.zero_as_missing)
        mappers.append(m)
    return mappers


# ---------------------------------------------------------------------------
# O(nnz) host binning
# ---------------------------------------------------------------------------

def zero_bins(mappers: Sequence[BinMapper]) -> np.ndarray:
    """Per-feature bin of the implicit value 0.0 (int32 [F]): the
    numerical default bin, or whatever bin category 0 maps to for
    categoricals (``num_bin - 1`` when 0 is not a kept category) —
    NOT ``BinMapper.default_bin``, which is pinned to 0 for
    categorical mappers."""
    return np.asarray([m.value_to_bin(0.0) for m in mappers], np.int32)


def bin_entries(sm: SparseMatrix, mappers: Sequence[BinMapper],
                used_feature_map: np.ndarray
                ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Bin the explicit entries of the USED (non-trivial) features.

    Returns (codes int32, feat int32 INNER feature index, rows int32)
    — the zero-suppressed coordinate planes. Entries of trivial
    (dropped) columns are discarded; entries binning INTO the zero bin
    are kept (they are redundant with the implicit background but
    harmless, and dropping them would cost a second pass)."""
    n, nf = sm.shape
    real_to_inner = np.full(nf, -1, np.int64)
    used = np.asarray(used_feature_map, np.int64)
    real_to_inner[used] = np.arange(len(used))
    cols, vals, order, bounds = _entries_by_column(sm, nf)
    rows_all = sm.rows()[order]
    codes = np.empty(len(vals), np.int32)
    keep = np.zeros(len(vals), bool)
    for real in used:
        sl = slice(bounds[real], bounds[real + 1])
        if sl.start == sl.stop:
            continue
        inner = int(real_to_inner[real])
        codes[sl] = mappers[inner].value_to_bin(vals[sl])
        keep[sl] = True
    feat = real_to_inner[cols[keep]].astype(np.int32)
    return codes[keep], feat, rows_all[keep].astype(np.int32)


def host_bins_from_sparse(sm: SparseMatrix, mappers,
                          used_feature_map, dtype) -> np.ndarray:
    """The [N, F_used] host bin matrix from CSR: implicit cells take
    ``zero_bins``, explicit entries ``value_to_bin`` — cell-for-cell
    equal to ``TpuDataset.bin_rows`` on the densified matrix (proven in
    tests/test_sparse.py over the NaN / ±kZeroThreshold / categorical
    edge cases). The result is the bin-storage tier's uint8/uint16/
    int32, so even this fallback is 8-64x below the float64 cliff."""
    n = sm.shape[0]
    f = len(mappers)
    if f == 0:
        return np.zeros((n, 1), dtype)
    bins = np.empty((n, f), dtype)
    bins[:] = zero_bins(mappers).astype(dtype)[None, :]
    codes, feat, rows = bin_entries(sm, mappers, used_feature_map)
    bins[rows, feat] = codes.astype(dtype)
    return bins
