"""ctypes binding for the native C++ text parser.

The parser itself lives in ``native/fast_parser.cpp`` (the reference's
IO layer is C++, src/io/parser.cpp — ours follows for the same reason:
tokenizing an 11M-row HIGGS file at Python string speed is minutes,
at C speed seconds). The shared object is compiled lazily with g++ into
the package directory and cached; every call site falls back to the
pure-Python parser (io/parser.py) when the toolchain or binary is
unavailable, and the Python parser stays the semantic oracle
(tests/test_native_parser.py asserts bitwise agreement).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

from ..utils import log

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, os.pardir, os.pardir, "native",
                    "fast_parser.cpp")
_SO = os.path.join(_HERE, "_fast_parser.so")

_lib = None
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    src = os.path.normpath(_SRC)
    if not os.path.exists(_SO) or (
            os.path.exists(src)
            and os.path.getmtime(src) > os.path.getmtime(_SO)):
        if not os.path.exists(src):
            return None
        try:
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-pthread",
                 "-o", _SO, src],
                check=True, capture_output=True, timeout=120)
        except (OSError, subprocess.SubprocessError) as e:
            log.debug("native parser build unavailable (%s); using the "
                      "python parser", e)
            return None
    try:
        lib = ctypes.CDLL(_SO)
    except OSError:
        return None
    try:
        _bind(lib)
    except AttributeError:
        # stale cached .so from an older version missing a symbol:
        # rebuild once, else fall back to the python paths
        try:
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-pthread",
                 "-o", _SO, src],
                check=True, capture_output=True, timeout=120)
            lib = ctypes.CDLL(_SO)
            _bind(lib)
        except (OSError, subprocess.SubprocessError, AttributeError):
            return None
    _lib = lib
    return _lib


def _bind(lib) -> None:
    lib.lgbm_tpu_parse_count.argtypes = [
        ctypes.c_char_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32)]
    lib.lgbm_tpu_parse_count.restype = ctypes.c_int
    lib.lgbm_tpu_parse_fill.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_int32, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_float),
        ctypes.c_int64, ctypes.c_int32]
    lib.lgbm_tpu_parse_fill.restype = ctypes.c_int
    lib.lgbm_tpu_bin_columns.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
        ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int32]
    lib.lgbm_tpu_bin_columns.restype = ctypes.c_int


def available() -> bool:
    return _load() is not None


def parse_file_native(filename: str, header: bool, label_idx: int
                      ) -> Optional[Tuple[np.ndarray,
                                          Optional[np.ndarray], int]]:
    """Parse with the native tokenizer.

    Returns (values [N, C], labels [N] or None, format) or None when
    the native path is unavailable. ``C`` excludes the label column.
    """
    lib = _load()
    if lib is None:
        return None
    rows = ctypes.c_int64(0)
    cols = ctypes.c_int32(0)
    fmt = ctypes.c_int32(0)
    rc = lib.lgbm_tpu_parse_count(
        filename.encode(), 1 if header else 0,
        ctypes.byref(rows), ctypes.byref(cols), ctypes.byref(fmt))
    if rc != 0:
        return None
    n, c, f = rows.value, cols.value, fmt.value
    # delimited: a label column only exists when label_idx is in range
    # (the python oracle's `width > label_idx` guard)
    has_label = label_idx >= 0 and (f == 2 or label_idx < c)
    feat_cols = c - (1 if (has_label and f != 2) else 0)
    feat_cols = max(feat_cols, 0)
    values = np.empty((n, feat_cols), np.float64)
    # zeros, not empty: rows without a label token (libsvm) keep 0.0
    # like the python oracle
    labels = np.zeros(n, np.float32) if has_label else None
    rc = lib.lgbm_tpu_parse_fill(
        filename.encode(), 1 if header else 0,
        np.int32(label_idx if has_label else -1), np.int32(f),
        values.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        (labels.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
         if labels is not None else None),
        np.int64(n), np.int32(feat_cols))
    if rc != 0:
        # rc 3 = ragged rows: the python parser pads and warns
        return None
    return values, labels, f


def bin_columns_native(X: np.ndarray, col_idx: np.ndarray,
                       bounds_list, r_len: np.ndarray,
                       nan_bin: np.ndarray) -> "Optional[np.ndarray]":
    """Bulk BinMapper::ValueToBin over numerical columns (threaded C++).

    X row-major [n, ncol] f32/f64; col_idx [f] source column per used
    feature; bounds_list: per-feature float64 upper-bound arrays;
    r_len[f]: searchsorted range; nan_bin[f]: NaN's bin or -1.
    Returns [n, f] uint8 or None when the native library is absent.
    """
    lib = _load()
    if lib is None:
        return None
    X = np.ascontiguousarray(X)
    if X.dtype == np.float32:
        xdtype = 1
    elif X.dtype == np.float64:
        xdtype = 0
    else:
        return None
    n, ncol = X.shape
    f = len(bounds_list)
    bounds = (np.concatenate(bounds_list).astype(np.float64)
              if f else np.zeros(0, np.float64))
    off = np.zeros(f + 1, np.int64)
    np.cumsum([len(b) for b in bounds_list], out=off[1:])
    out = np.empty((n, f), np.uint8)
    col_idx = np.ascontiguousarray(col_idx, np.int32)
    r_len = np.ascontiguousarray(r_len, np.int32)
    nan_bin = np.ascontiguousarray(nan_bin, np.int32)
    bounds = np.ascontiguousarray(bounds)
    off = np.ascontiguousarray(off)
    nthreads = min(16, os.cpu_count() or 1)
    rc = lib.lgbm_tpu_bin_columns(
        X.ctypes.data_as(ctypes.c_void_p), np.int64(n), np.int32(ncol),
        np.int32(xdtype),
        col_idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        np.int32(f),
        bounds.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        r_len.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        nan_bin.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        np.int32(nthreads))
    return out if rc == 0 else None
