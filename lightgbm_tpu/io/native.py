"""ctypes binding for the native C++ text parser.

The parser itself lives in ``native/fast_parser.cpp`` (the reference's
IO layer is C++, src/io/parser.cpp — ours follows for the same reason:
tokenizing an 11M-row HIGGS file at Python string speed is minutes,
at C speed seconds). The shared object is compiled lazily with g++ into
the package directory and cached; every call site falls back to the
pure-Python parser (io/parser.py) when the toolchain or binary is
unavailable, and the Python parser stays the semantic oracle
(tests/test_native_parser.py asserts bitwise agreement).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

from ..utils import log

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, os.pardir, os.pardir, "native",
                    "fast_parser.cpp")
_SO = os.path.join(_HERE, "_fast_parser.so")

_lib = None
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    src = os.path.normpath(_SRC)
    if not os.path.exists(_SO) or (
            os.path.exists(src)
            and os.path.getmtime(src) > os.path.getmtime(_SO)):
        if not os.path.exists(src):
            return None
        try:
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-o", _SO, src],
                check=True, capture_output=True, timeout=120)
        except (OSError, subprocess.SubprocessError) as e:
            log.debug("native parser build unavailable (%s); using the "
                      "python parser", e)
            return None
    try:
        lib = ctypes.CDLL(_SO)
    except OSError:
        return None
    lib.lgbm_tpu_parse_count.argtypes = [
        ctypes.c_char_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32)]
    lib.lgbm_tpu_parse_count.restype = ctypes.c_int
    lib.lgbm_tpu_parse_fill.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_int32, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_float),
        ctypes.c_int64, ctypes.c_int32]
    lib.lgbm_tpu_parse_fill.restype = ctypes.c_int
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def parse_file_native(filename: str, header: bool, label_idx: int
                      ) -> Optional[Tuple[np.ndarray,
                                          Optional[np.ndarray], int]]:
    """Parse with the native tokenizer.

    Returns (values [N, C], labels [N] or None, format) or None when
    the native path is unavailable. ``C`` excludes the label column.
    """
    lib = _load()
    if lib is None:
        return None
    rows = ctypes.c_int64(0)
    cols = ctypes.c_int32(0)
    fmt = ctypes.c_int32(0)
    rc = lib.lgbm_tpu_parse_count(
        filename.encode(), 1 if header else 0,
        ctypes.byref(rows), ctypes.byref(cols), ctypes.byref(fmt))
    if rc != 0:
        return None
    n, c, f = rows.value, cols.value, fmt.value
    # delimited: a label column only exists when label_idx is in range
    # (the python oracle's `width > label_idx` guard)
    has_label = label_idx >= 0 and (f == 2 or label_idx < c)
    feat_cols = c - (1 if (has_label and f != 2) else 0)
    feat_cols = max(feat_cols, 0)
    values = np.empty((n, feat_cols), np.float64)
    # zeros, not empty: rows without a label token (libsvm) keep 0.0
    # like the python oracle
    labels = np.zeros(n, np.float32) if has_label else None
    rc = lib.lgbm_tpu_parse_fill(
        filename.encode(), 1 if header else 0,
        np.int32(label_idx if has_label else -1), np.int32(f),
        values.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        (labels.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
         if labels is not None else None),
        np.int64(n), np.int32(feat_cols))
    if rc != 0:
        # rc 3 = ragged rows: the python parser pads and warns
        return None
    return values, labels, f
