"""Dataset loading from text / binary files.

TPU-native counterpart of the reference DatasetLoader
(reference: src/io/dataset_loader.cpp:161-1111 LoadFromFile /
ConstructBinMappersFromTextData; column resolution
dataset_loader.cpp:53-159; sidecar files src/io/metadata.cpp:324-431).

Responsibilities: resolve label/weight/group/ignore/categorical columns
(by index or ``name:`` prefix against the header), parse the text file
(io/parser.py), split metadata columns out of the feature matrix, load
``.weight`` / ``.query`` / ``.init`` sidecar files, and construct the
binned TpuDataset. Binary files (save_binary) short-circuit straight to
TpuDataset.load_binary like dataset_loader.cpp:252-257.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..config import Config
from ..utils import log
from .dataset import Metadata, TpuDataset
from .parser import parse_file


def _parse_column_spec(spec: str, names: List[str], what: str) -> int:
    """'name:foo' or integer index -> index; -1 when unset
    (dataset_loader.cpp:53-112)."""
    spec = spec.strip()
    if not spec:
        return -1
    if spec.startswith("name:"):
        name = spec[5:]
        if name not in names:
            log.fatal(f"Could not find {what} column {name!r} in data file "
                      "(set header=true?)")
        return names.index(name)
    try:
        return int(spec)
    except ValueError:
        log.fatal(f"Bad {what} column spec {spec!r}; use an index or "
                  "'name:column_name'")


def _parse_multi_column_spec(spec: str, names: List[str],
                             what: str) -> Set[int]:
    """Comma-separated indices or 'name:a,b,c' (dataset_loader.cpp:113-159)."""
    spec = spec.strip()
    if not spec:
        return set()
    out: Set[int] = set()
    if spec.startswith("name:"):
        for name in spec[5:].split(","):
            name = name.strip()
            if not name:
                continue
            if name not in names:
                log.fatal(f"Could not find {what} column {name!r} in data "
                          "file (set header=true?)")
            out.add(names.index(name))
        return out
    for tok in spec.split(","):
        tok = tok.strip()
        if tok:
            out.add(int(tok))
    return out


def _read_float_file(path: str) -> Optional[np.ndarray]:
    """One float per line (metadata.cpp LoadWeights/LoadQueryBoundaries)."""
    if not os.path.isfile(path):
        return None
    vals = []
    with open(path) as fh:
        for ln in fh:
            ln = ln.strip()
            if ln and not ln.startswith("#"):
                vals.append([float(x) for x in ln.replace(",", " ").split()])
    if not vals:
        return None
    arr = np.asarray(vals, np.float64)
    return arr[:, 0] if arr.shape[1] == 1 else arr


class DatasetLoader:
    """LoadFromFile / column bookkeeping (dataset_loader.cpp:24-52)."""

    def __init__(self, config: Config,
                 predict_fun=None):
        self.config = config

    # -- text -> TpuDataset --------------------------------------------------

    def load_from_file(self, filename: str,
                       reference: Optional[TpuDataset] = None) -> TpuDataset:
        """LoadFromFile (dataset_loader.cpp:161-257). ``reference`` set
        = validation data binned with the train mappers (CreateValid)."""
        cfg = self.config
        if TpuDataset.is_binary_file(filename):
            log.info("Loading binary dataset %s", filename)
            return TpuDataset.load_binary(filename, cfg)
        bin_cache = filename + ".bin"
        if (cfg.enable_load_from_binary_file and reference is None
                and TpuDataset.is_binary_file(bin_cache)):
            log.info("Loading dataset from binary cache %s", bin_cache)
            return TpuDataset.load_binary(bin_cache, cfg)

        X, meta, names, categorical = self._parse_with_metadata(filename)
        ds = TpuDataset(cfg)
        ds.construct_from_matrix(
            X, meta, categorical=categorical, reference=reference,
            feature_names=names or None)
        log.info("Finished loading %s: %d rows, %d used features",
                 filename, ds.num_data, ds.num_features)
        if cfg.save_binary and reference is None:
            ds.save_binary(bin_cache)
        return ds

    def _parse_with_metadata(self, filename: str
                             ) -> Tuple[np.ndarray, Metadata, List[str],
                                        List[int]]:
        cfg = self.config
        # resolve the label against the raw header line (full column
        # set, label included) without parsing the whole file twice
        full_names: List[str] = []
        if cfg.header:
            with open(filename) as fh:
                head = fh.readline()
            from .parser import detect_format
            delim = {"csv": ",", "tsv": "\t"}.get(
                detect_format([head]), "\t")
            full_names = [t.strip() for t in head.rstrip("\r\n")
                          .split(delim)]
        label_all = _parse_column_spec(
            cfg.label_column, full_names,
            "label") if cfg.label_column else 0
        if label_all < 0:
            label_all = 0
        parsed, header_names = parse_file(filename, header=cfg.header,
                                          label_idx=label_all)
        X = parsed.values
        label = parsed.label

        # weight/group/ignore indices do NOT count the label column
        # (docs/Parameters: "index starts from 0 ... doesn't count the
        # label column"); names resolve against the post-label layout.
        feat_names = list(header_names)
        weight_idx = _parse_column_spec(cfg.weight_column, feat_names,
                                        "weight") if cfg.weight_column else -1
        group_idx = _parse_column_spec(cfg.group_column, feat_names,
                                       "group") if cfg.group_column else -1
        ignore = _parse_multi_column_spec(cfg.ignore_column, feat_names,
                                          "ignore")
        categorical = _parse_multi_column_spec(
            cfg.categorical_feature, feat_names, "categorical")

        weight = X[:, weight_idx].astype(np.float32) if weight_idx >= 0 \
            else None
        group_col = X[:, group_idx] if group_idx >= 0 else None

        drop = sorted({i for i in (weight_idx, group_idx) if i >= 0}
                      | {i for i in ignore if 0 <= i < X.shape[1]})
        if drop:
            keep = [i for i in range(X.shape[1]) if i not in drop]
            X = X[:, keep]
            remap = {old: new for new, old in enumerate(keep)}
            categorical = {remap[c] for c in categorical if c in remap}
            if feat_names:
                feat_names = [feat_names[i] for i in keep]

        # sidecars (metadata.cpp:324-431): <file>.weight, <file>.query,
        # init scores from config or <file>.init
        if weight is None:
            w = _read_float_file(filename + ".weight")
            if w is not None:
                weight = np.asarray(w, np.float32).reshape(-1)
                log.info("Loading weights from %s.weight", filename)
        group = None
        if group_col is not None:
            # query-id column -> boundaries via run-length counts
            ids = np.asarray(group_col)
            change = np.nonzero(np.diff(ids))[0] + 1
            bounds = np.concatenate([[0], change, [len(ids)]])
            group = np.diff(bounds)
        else:
            q = _read_float_file(filename + ".query")
            if q is None:
                q = _read_float_file(filename + ".query.weight")
            if q is not None:
                group = np.asarray(q, np.int64).reshape(-1)
                log.info("Loading query boundaries from %s.query", filename)
        init_score = None
        init_path = cfg.initscore_filename or (filename + ".init")
        isc = _read_float_file(init_path)
        if isc is not None:
            init_score = np.asarray(isc, np.float64)
            if init_score.ndim == 2:       # [N, K] column-major flatten
                init_score = init_score.T.reshape(-1)
            log.info("Loading initial scores from %s", init_path)

        meta = Metadata(label=label, weight=weight, group=group,
                        init_score=init_score)
        return X, meta, feat_names, sorted(categorical)

    # -- prediction-side text load ------------------------------------------

    def load_predict_matrix(self, filename: str, num_features: int
                            ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """Parse a file for prediction: the label column may be absent
        when rows carry exactly num_features columns (Predictor path,
        parser.cpp:25-62 via infer_label_idx)."""
        cfg = self.config
        parsed, _ = parse_file(filename, header=cfg.header, label_idx=0,
                               num_features_hint=num_features)
        X = parsed.values
        if X.shape[1] < num_features:
            X = np.pad(X, ((0, 0), (0, num_features - X.shape[1])),
                       constant_values=np.nan)
        elif X.shape[1] > num_features:
            X = X[:, :num_features]
        return X, parsed.label
