"""Dataset loading from text / binary files.

TPU-native counterpart of the reference DatasetLoader
(reference: src/io/dataset_loader.cpp:161-1111 LoadFromFile /
ConstructBinMappersFromTextData; column resolution
dataset_loader.cpp:53-159; sidecar files src/io/metadata.cpp:324-431).

Responsibilities: resolve label/weight/group/ignore/categorical columns
(by index or ``name:`` prefix against the header), parse the text file
(io/parser.py), split metadata columns out of the feature matrix, load
``.weight`` / ``.query`` / ``.init`` sidecar files, and construct the
binned TpuDataset. Binary files (save_binary) short-circuit straight to
TpuDataset.load_binary like dataset_loader.cpp:252-257.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..config import Config
from ..obs import registry as obs
from ..utils import log
from .dataset import Metadata, TpuDataset
from .file_io import open_file
from .parser import parse_file


def _parse_column_spec(spec: str, names: List[str], what: str) -> int:
    """'name:foo' or integer index -> index; -1 when unset
    (dataset_loader.cpp:53-112)."""
    spec = spec.strip()
    if not spec:
        return -1
    if spec.startswith("name:"):
        name = spec[5:]
        if name not in names:
            log.fatal(f"Could not find {what} column {name!r} in data file "
                      "(set header=true?)")
        return names.index(name)
    try:
        return int(spec)
    except ValueError:
        log.fatal(f"Bad {what} column spec {spec!r}; use an index or "
                  "'name:column_name'")


def _parse_multi_column_spec(spec: str, names: List[str],
                             what: str) -> Set[int]:
    """Comma-separated indices or 'name:a,b,c' (dataset_loader.cpp:113-159)."""
    spec = spec.strip()
    if not spec:
        return set()
    out: Set[int] = set()
    if spec.startswith("name:"):
        for name in spec[5:].split(","):
            name = name.strip()
            if not name:
                continue
            if name not in names:
                log.fatal(f"Could not find {what} column {name!r} in data "
                          "file (set header=true?)")
            out.add(names.index(name))
        return out
    for tok in spec.split(","):
        tok = tok.strip()
        if tok:
            out.add(int(tok))
    return out


def _read_float_file(path: str) -> Optional[np.ndarray]:
    """One float per line (metadata.cpp LoadWeights/LoadQueryBoundaries)."""
    if not os.path.isfile(path):
        return None
    vals = []
    with open(path) as fh:
        for ln in fh:
            ln = ln.strip()
            if ln and not ln.startswith("#"):
                vals.append([float(x) for x in ln.replace(",", " ").split()])
    if not vals:
        return None
    arr = np.asarray(vals, np.float64)
    return arr[:, 0] if arr.shape[1] == 1 else arr


class DatasetLoader:
    """LoadFromFile / column bookkeeping (dataset_loader.cpp:24-52)."""

    def __init__(self, config: Config,
                 predict_fun=None):
        self.config = config

    # -- text -> TpuDataset --------------------------------------------------

    def load_from_file(self, filename: str,
                       reference: Optional[TpuDataset] = None) -> TpuDataset:
        """LoadFromFile (dataset_loader.cpp:161-257). ``reference`` set
        = validation data binned with the train mappers (CreateValid)."""
        cfg = self.config
        if TpuDataset.is_binary_file(filename):
            log.info("Loading binary dataset %s", filename)
            return TpuDataset.load_binary(filename, cfg)
        bin_cache = filename + ".bin"
        if (cfg.enable_load_from_binary_file and reference is None
                and TpuDataset.is_binary_file(bin_cache)):
            log.info("Loading dataset from binary cache %s", bin_cache)
            return TpuDataset.load_binary(bin_cache, cfg)

        if cfg.two_round or cfg.tpu_out_of_core == 1:
            ds = self._load_two_round(filename, reference)
        else:
            X, meta, names, categorical = self._parse_with_metadata(
                filename)
            ds = TpuDataset(cfg)
            ds.construct_from_matrix(
                X, meta, categorical=categorical, reference=reference,
                feature_names=names or None)
        log.info("Finished loading %s: %d rows, %d used features",
                 filename, ds.num_data, ds.num_features)
        if cfg.save_binary and reference is None:
            ds.save_binary(bin_cache)
        return ds

    # -- two-round (memory-light) loading ------------------------------------

    def _data_lines(self, filename: str):
        """Yield data lines: header/comments/blanks skipped
        (TextReader parity, utils/text_reader.h)."""
        header_pending = self.config.header
        with open_file(filename) as fh:
            for ln in fh:
                t = ln.strip()
                if not t or t.startswith("#"):
                    continue
                if header_pending:
                    header_pending = False
                    continue
                yield ln.rstrip("\r\n")

    def _load_two_round(self, filename: str,
                        reference: Optional[TpuDataset] = None,
                        chunk_rows: int = 0) -> TpuDataset:
        """two_round=true (or tpu_out_of_core=1): the reference's
        memory-light path (dataset_loader.cpp LoadFromFile with
        two_round — SampleTextDataFromFile then a second streaming
        pass, :196-235/:657-704). Pass 1 counts rows and parses only a
        sampled subset to build the bin mappers; pass 2 re-streams the
        file in ``chunk_rows`` blocks (tpu_ooc_block_rows; 0 = 256k),
        binning each block straight into the uint8 matrix — the full
        float matrix never exists. With device ingest on, each block
        feeds the double-buffered device binner and even the host bin
        matrix disappears: peak RSS is bounded by the block size, not
        N (tpu_out_of_core=0 pins the host-bins fallback)."""
        cfg = self.config
        if chunk_rows <= 0:
            chunk_rows = int(cfg.tpu_ooc_block_rows) or (1 << 18)
        from .dataset import find_column_mappers
        from .parser import (_first_data_lines, detect_format,
                             parse_delimited, parse_libsvm)
        first, head = _first_data_lines(filename, 2, cfg.header, True)
        fmt = detect_format(first)
        delim = "\t" if fmt == "tsv" else ","
        full_names = ([t.strip() for t in head.split(delim)]
                      if cfg.header and head else [])
        label_all = _parse_column_spec(
            cfg.label_column, full_names,
            "label") if cfg.label_column else 0
        if label_all < 0:
            label_all = 0

        def parse_lines(lines, ncol_hint=0):
            if fmt == "libsvm":
                return parse_libsvm(lines, label_all, ncol_hint)
            return parse_delimited(lines, delim, label_all)

        # pass 1 (ONE scan): count rows, reservoir-sample the bin-
        # construction lines, and for libsvm track the true column
        # count across the WHOLE file (features absent from the sample
        # must still get bin slots — trivial, but present)
        cap = max(int(cfg.bin_construct_sample_cnt), 1)
        rng = np.random.default_rng(cfg.data_random_seed)
        reservoir: List[str] = []
        n = 0
        libsvm_maxidx = -1
        for ln in self._data_lines(filename):
            if fmt == "libsvm":
                # indices ascend in well-formed libsvm rows: the last
                # pair carries the row's max feature index
                tail = ln.rstrip().rsplit(None, 1)
                if len(tail) == 2 and ":" in tail[1]:
                    try:
                        libsvm_maxidx = max(
                            libsvm_maxidx,
                            int(tail[1].split(":", 1)[0]))
                    except ValueError:
                        pass
            if n < cap:
                reservoir.append(ln)
            else:
                j = int(rng.integers(0, n + 1))
                if j < cap:
                    reservoir[j] = ln
            n += 1
        if n == 0:
            log.fatal(f"Data file {filename} is empty")
        sparsed = parse_lines(reservoir,
                              libsvm_maxidx + 1 if fmt == "libsvm"
                              else 0)
        ncol = max(sparsed.num_columns,
                   libsvm_maxidx + 1 if fmt == "libsvm" else 0)
        # rows missing trailing delimited columns bin as missing (the
        # one-round parser's semantics); absent libsvm pairs are 0
        pad_value = 0.0 if fmt == "libsvm" else np.nan

        feat_names = list(full_names)
        if feat_names and sparsed.label is not None \
                and len(feat_names) > ncol:
            feat_names.pop(max(label_all, 0))
        (weight_idx, group_idx, keep_cols, categorical,
         feat_names) = self._resolve_columns(feat_names, ncol)

        ds = TpuDataset(cfg)
        ds.num_data = n
        ds.num_total_features = len(keep_cols)
        ds.feature_names = (feat_names if feat_names else
                            [f"Column_{i}"
                             for i in range(len(keep_cols))])
        if reference is not None:
            ds._reference = reference
            ds.mappers = reference.mappers
            ds.used_feature_map = reference.used_feature_map
            ds.real_to_inner = reference.real_to_inner
            ds.max_bin_global = reference.max_bin_global
            ds.feature_names = reference.feature_names
            ds.num_total_features = reference.num_total_features
        else:
            Xs = sparsed.values
            if Xs.shape[1] < ncol:
                Xs = np.pad(Xs, ((0, 0), (0, ncol - Xs.shape[1])),
                            constant_values=pad_value)
            ds._set_mappers(find_column_mappers(
                Xs[:, keep_cols], cfg, categorical,
                total_rows=n, presampled=True))

        # pass 2: stream + bin. With device ingest enabled the chunks
        # feed the jitted device binner (io/ingest.py) and the [F, N]
        # matrix assembles directly on device — parsing the next text
        # block is the host half of the double buffer, so transfer and
        # binning overlap the tokenizer. Host path otherwise.
        f_used = max(len(ds.mappers), 1)
        dtype = np.uint8 if ds.max_bin_global <= 256 else np.int32
        from .ingest import (DeviceBinner, IngestUnsupported,
                             ingest_enabled, ingest_mesh)
        stream = None
        efb_live = (reference is None and cfg.enable_bundle
                    and ds.num_features > 1)
        if (cfg.tpu_out_of_core != 0 and ingest_enabled(cfg)
                and ds.mappers
                and (reference is None or reference.bundles is None)):
            try:
                binner = DeviceBinner(ds.mappers, ds.used_feature_map,
                                      cfg, np.float64)
            except IngestUnsupported as e:
                log.debug("two_round device ingest unavailable (%s); "
                          "host binner", e)
            else:
                # valid sets ride as passenger columns of the grower
                # matrix (models/gbdt.py) — only the train set's rows
                # are worth sharding at ingest time
                mesh = ingest_mesh(cfg) if reference is None else None
                import jax
                if (mesh is not None and efb_live
                        and jax.process_count() > 1):
                    # an engaged EFB probe would need the global array
                    # materialized on one host, which a multi-process
                    # mesh cannot provide — host binner keeps the
                    # bundling decision bit-identical
                    log.debug("two_round: EFB probe + multi-process "
                              "mesh; host binner")
                elif mesh is not None:
                    stream = binner.start_sharded_stream(mesh, n)
                else:
                    stream = binner.start_stream()
        bins = (None if stream is not None
                else np.zeros((n, f_used), dtype))
        # EFB probe sample: the same rng(3) rows find_bundles would
        # draw, collected RAW while streaming and host-binned at the
        # end, so the bundling decision is bit-identical to the host
        # path's (io/dataset.py _efb_would_bundle has the in-memory
        # analog)
        efb_sorted = None
        efb_rows: List[np.ndarray] = []
        if stream is not None and efb_live:
            from .efb import sample_rows_for_probe
            idx = sample_rows_for_probe(n)
            efb_sorted = np.arange(n) if idx is None else np.sort(idx)
        label = np.zeros(n, np.float32)
        weight = np.zeros(n, np.float32) if weight_idx >= 0 else None
        group_col = np.zeros(n, np.float64) if group_idx >= 0 else None
        row = 0
        buf: List[str] = []

        def flush(buf):
            nonlocal row
            if not buf:
                return
            obs.counter("ooc/blocks").add(1)
            obs.counter("ooc/disk_bytes").add(
                sum(len(s) + 1 for s in buf))
            p = parse_lines(buf, ncol)
            Xc = p.values
            if Xc.shape[1] < ncol:
                # delimited rows missing trailing columns -> missing
                # (NaN, matching the one-round parser); absent libsvm
                # pairs -> 0 (libsvm sparse semantics)
                Xc = np.pad(Xc, ((0, 0), (0, ncol - Xc.shape[1])),
                            constant_values=pad_value)
            elif Xc.shape[1] > ncol:
                if fmt == "libsvm":
                    # pass-1 sized columns from each row's LAST pair;
                    # exceeding it means some row has non-ascending
                    # feature indices — truncating would silently drop
                    # features, so fail loudly instead
                    log.fatal(
                        f"two_round: libsvm row block has "
                        f"{Xc.shape[1]} columns, expected {ncol}; "
                        "feature indices are not ascending within a "
                        "row. Sort indices or load with "
                        "two_round=false")
                log.warning("two_round: row block has %d columns, "
                            "expected %d; extra columns ignored",
                            Xc.shape[1], ncol)
                Xc = Xc[:, :ncol]
            k = Xc.shape[0]
            if p.label is not None:
                label[row:row + k] = p.label
            if weight is not None:
                weight[row:row + k] = Xc[:, weight_idx]
            if group_col is not None:
                group_col[row:row + k] = Xc[:, group_idx]
            Xf = Xc[:, keep_cols]
            if stream is not None:
                if efb_sorted is not None:
                    lo = np.searchsorted(efb_sorted, row)
                    hi = np.searchsorted(efb_sorted, row + k)
                    if hi > lo:
                        efb_rows.append(Xf[efb_sorted[lo:hi] - row])
                stream.feed(Xf)
            else:
                bins[row:row + k] = ds.bin_rows(Xf)
            obs.counter("loader/two_round_blocks").add(1)
            obs.counter("loader/two_round_rows").add(k)
            row += k

        for ln in self._data_lines(filename):
            buf.append(ln)
            if len(buf) >= chunk_rows:
                flush(buf)
                buf = []
        flush(buf)
        if stream is None:
            ds.bins = bins
        else:
            dev = stream.finish()
            bundled = False
            if efb_sorted is not None and efb_rows:
                from .efb import would_bundle
                bundled = would_bundle(
                    ds.bin_rows(np.concatenate(efb_rows)),
                    ds.mappers, cfg.max_conflict_rate)
            if bundled:
                # EFB engages on this data: materialize the host
                # layout so _apply_efb bundles the same full matrix
                # the host path would have built
                log.info("two_round: EFB bundles this data; "
                         "materializing device bins on host")
                ds.bins = np.ascontiguousarray(
                    np.asarray(dev)[:, :n].T).astype(dtype, copy=False)
            else:
                ds.bins_t_dev = dev
                ds.bins_t_dev_pad = dev.shape[1] - n
                log.info("two_round: streamed device ingest "
                         "(%d rows%s)", n,
                         f", {ds.bins_t_dev_pad} pad"
                         if ds.bins_t_dev_pad else "")
        ds.metadata = self._assemble_metadata(
            filename, label if sparsed.label is not None else None,
            weight, group_col)
        ds.metadata.check_or_partition(n)
        if ds.bins is not None:
            ds._apply_efb()  # handles both fresh and reference bundles
        try:
            import resource
            obs.gauge("ooc/rss_peak_mb").set(
                resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
                / 1024.0)
        except ImportError:        # non-POSIX host
            pass
        log.info("two_round load: %d rows binned in %d-row blocks",
                 n, chunk_rows)
        return ds

    def _parse_with_metadata(self, filename: str
                             ) -> Tuple[np.ndarray, Metadata, List[str],
                                        List[int]]:
        cfg = self.config
        # resolve the label against the raw header line (full column
        # set, label included) without parsing the whole file twice
        full_names: List[str] = []
        if cfg.header:
            with open_file(filename) as fh:
                head = fh.readline()
            from .parser import detect_format
            delim = {"csv": ",", "tsv": "\t"}.get(
                detect_format([head]), "\t")
            full_names = [t.strip() for t in head.rstrip("\r\n")
                          .split(delim)]
        label_all = _parse_column_spec(
            cfg.label_column, full_names,
            "label") if cfg.label_column else 0
        if label_all < 0:
            label_all = 0
        parsed, header_names = parse_file(filename, header=cfg.header,
                                          label_idx=label_all)
        X = parsed.values
        label = parsed.label

        (weight_idx, group_idx, keep_cols, categorical,
         feat_names) = self._resolve_columns(list(header_names),
                                             X.shape[1])
        weight = X[:, weight_idx].astype(np.float32) if weight_idx >= 0 \
            else None
        group_col = X[:, group_idx] if group_idx >= 0 else None
        if len(keep_cols) != X.shape[1]:
            X = X[:, keep_cols]

        meta = self._assemble_metadata(filename, label, weight, group_col)
        return X, meta, feat_names, categorical

    def _resolve_columns(self, feat_names: List[str], ncol: int):
        """weight/group/ignore/categorical column resolution. Indices
        do NOT count the label column (docs/Parameters: "index starts
        from 0 ... doesn't count the label column"); names resolve
        against the post-label layout. Returns
        (weight_idx, group_idx, keep_cols, categorical, kept_names)
        with ``categorical`` remapped to the kept layout."""
        cfg = self.config
        weight_idx = _parse_column_spec(
            cfg.weight_column, feat_names,
            "weight") if cfg.weight_column else -1
        group_idx = _parse_column_spec(
            cfg.group_column, feat_names,
            "group") if cfg.group_column else -1
        ignore = _parse_multi_column_spec(cfg.ignore_column, feat_names,
                                          "ignore")
        categorical = _parse_multi_column_spec(
            cfg.categorical_feature, feat_names, "categorical")
        drop = sorted({i for i in (weight_idx, group_idx) if i >= 0}
                      | {i for i in ignore if 0 <= i < ncol})
        keep_cols = [i for i in range(ncol) if i not in drop]
        remap = {old: new for new, old in enumerate(keep_cols)}
        categorical = sorted({remap[c] for c in categorical
                              if c in remap})
        if feat_names:
            feat_names = [feat_names[i] for i in keep_cols
                          if i < len(feat_names)]
        return weight_idx, group_idx, keep_cols, categorical, feat_names

    def _assemble_metadata(self, filename: str, label, weight,
                           group_col) -> Metadata:
        """Metadata from in-file columns + sidecar files
        (metadata.cpp:324-431): <file>.weight, <file>.query, init scores
        from config or <file>.init."""
        cfg = self.config
        if weight is None:
            w = _read_float_file(filename + ".weight")
            if w is not None:
                weight = np.asarray(w, np.float32).reshape(-1)
                log.info("Loading weights from %s.weight", filename)
        group = None
        if group_col is not None:
            # query-id column -> boundaries via run-length counts
            ids = np.asarray(group_col)
            change = np.nonzero(np.diff(ids))[0] + 1
            bounds = np.concatenate([[0], change, [len(ids)]])
            group = np.diff(bounds)
        else:
            q = _read_float_file(filename + ".query")
            if q is None:
                q = _read_float_file(filename + ".query.weight")
            if q is not None:
                group = np.asarray(q, np.int64).reshape(-1)
                log.info("Loading query boundaries from %s.query", filename)
        init_score = None
        init_path = cfg.initscore_filename or (filename + ".init")
        isc = _read_float_file(init_path)
        if isc is not None:
            init_score = np.asarray(isc, np.float64)
            if init_score.ndim == 2:       # [N, K] column-major flatten
                init_score = init_score.T.reshape(-1)
            log.info("Loading initial scores from %s", init_path)
        return Metadata(label=label, weight=weight, group=group,
                        init_score=init_score)

    # -- prediction-side text load ------------------------------------------

    def load_predict_matrix(self, filename: str, num_features: int
                            ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """Parse a file for prediction: the label column may be absent
        when rows carry exactly num_features columns (Predictor path,
        parser.cpp:25-62 via infer_label_idx)."""
        cfg = self.config
        parsed, _ = parse_file(filename, header=cfg.header, label_idx=0,
                               num_features_hint=num_features)
        X = parsed.values
        if X.shape[1] < num_features:
            X = np.pad(X, ((0, 0), (0, num_features - X.shape[1])),
                       constant_values=np.nan)
        elif X.shape[1] > num_features:
            X = X[:, :num_features]
        return X, parsed.label
