"""Feature binning (value -> bin index mapping).

TPU-native counterpart of the reference BinMapper (reference:
include/LightGBM/bin.h:61, src/io/bin.cpp:74-365). Host-side, numpy-based:
binning is one-time preprocessing; the binned uint8/uint16 matrix is what
lives in HBM. Semantics follow the reference exactly so that bin boundaries
(and therefore trees) match:

- ``greedy_find_bin``       <- GreedyFindBin (src/io/bin.cpp:74)
- ``find_bin_with_zero_as_one_bin`` <- FindBinWithZeroAsOneBin (bin.cpp:152)
- ``BinMapper.find_bin``    <- BinMapper::FindBin (bin.cpp:208)
- ``BinMapper.value_to_bin`` <- BinMapper::ValueToBin (bin.h:452)
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..utils import log

KZERO_THRESHOLD = 1e-35          # meta.h:40
_DOUBLE_EPS = 1e-300


class MissingType:
    NONE = 0
    ZERO = 1
    NAN = 2


class BinType:
    NUMERICAL = 0
    CATEGORICAL = 1


def _get_double_upper_bound(x: float) -> float:
    """Common::GetDoubleUpperBound — smallest double > x representable as the
    midpoint; the reference nudges up by ulp. np.nextafter matches."""
    return float(np.nextafter(x, np.inf))


def _check_double_equal(a: float, b: float) -> bool:
    """Common::CheckDoubleEqualOrdered(a, b): a >= b after upper-bounding."""
    upper = np.nextafter(a, np.inf)
    return bool(upper >= b)


def greedy_find_bin(distinct_values: np.ndarray, counts: np.ndarray,
                    max_bin: int, total_cnt: int,
                    min_data_in_bin: int) -> List[float]:
    """Quantile-ish greedy binning over distinct values (bin.cpp:74-150)."""
    num_distinct = len(distinct_values)
    bin_upper_bound: List[float] = []
    assert max_bin > 0
    if num_distinct <= max_bin:
        cur_cnt_inbin = 0
        for i in range(num_distinct - 1):
            cur_cnt_inbin += int(counts[i])
            if cur_cnt_inbin >= min_data_in_bin:
                val = _get_double_upper_bound(
                    (float(distinct_values[i]) + float(distinct_values[i + 1])) / 2.0)
                if not bin_upper_bound or not _check_double_equal(bin_upper_bound[-1], val):
                    bin_upper_bound.append(val)
                    cur_cnt_inbin = 0
        bin_upper_bound.append(np.inf)
    else:
        if min_data_in_bin > 0:
            max_bin = min(max_bin, int(total_cnt // min_data_in_bin))
            max_bin = max(max_bin, 1)
        mean_bin_size = total_cnt / max_bin
        rest_bin_cnt = max_bin
        rest_sample_cnt = int(total_cnt)
        is_big = counts >= mean_bin_size
        rest_bin_cnt -= int(is_big.sum())
        rest_sample_cnt -= int(counts[is_big].sum())
        mean_bin_size = rest_sample_cnt / max(rest_bin_cnt, 1)
        upper_bounds = [np.inf] * max_bin
        lower_bounds = [np.inf] * max_bin

        bin_cnt = 0
        lower_bounds[0] = float(distinct_values[0])
        if not is_big.any():
            # fast path (the common continuous-feature case: every count
            # below the mean): the greedy scan reduces to "next boundary
            # = first prefix-sum >= base + mean", one searchsorted per
            # bin instead of a python loop over every distinct value
            csum = np.cumsum(np.asarray(counts, np.int64))
            base = 0
            while bin_cnt < max_bin - 1:
                mean_bin_size = (rest_sample_cnt - base) \
                    / max(rest_bin_cnt - bin_cnt, 1)
                i = int(np.searchsorted(csum[:num_distinct - 1],
                                        base + mean_bin_size, side="left"))
                if i > num_distinct - 2:
                    break
                upper_bounds[bin_cnt] = float(distinct_values[i])
                bin_cnt += 1
                lower_bounds[bin_cnt] = float(distinct_values[i + 1])
                base = int(csum[i])
        else:
            cur_cnt_inbin = 0
            for i in range(num_distinct - 1):
                if not is_big[i]:
                    rest_sample_cnt -= int(counts[i])
                cur_cnt_inbin += int(counts[i])
                if (is_big[i] or cur_cnt_inbin >= mean_bin_size or
                        (is_big[i + 1] and cur_cnt_inbin
                         >= max(1.0, mean_bin_size * 0.5))):
                    upper_bounds[bin_cnt] = float(distinct_values[i])
                    bin_cnt += 1
                    lower_bounds[bin_cnt] = float(distinct_values[i + 1])
                    if bin_cnt >= max_bin - 1:
                        break
                    cur_cnt_inbin = 0
                    if not is_big[i]:
                        rest_bin_cnt -= 1
                        mean_bin_size = rest_sample_cnt \
                            / max(rest_bin_cnt, 1)
        bin_cnt += 1
        for i in range(bin_cnt - 1):
            val = _get_double_upper_bound((upper_bounds[i] + lower_bounds[i + 1]) / 2.0)
            if not bin_upper_bound or not _check_double_equal(bin_upper_bound[-1], val):
                bin_upper_bound.append(val)
        bin_upper_bound.append(np.inf)
    return bin_upper_bound


def find_bin_with_zero_as_one_bin(distinct_values: np.ndarray,
                                  counts: np.ndarray, max_bin: int,
                                  total_sample_cnt: int,
                                  min_data_in_bin: int) -> List[float]:
    """Dedicated zero bin straddling ±kZeroThreshold (bin.cpp:152-206)."""
    dv = np.asarray(distinct_values, dtype=np.float64)
    cnts = np.asarray(counts, dtype=np.int64)
    left_mask = dv <= -KZERO_THRESHOLD
    right_mask = dv > KZERO_THRESHOLD
    zero_mask = ~left_mask & ~right_mask
    left_cnt_data = int(cnts[left_mask].sum())
    cnt_zero = int(cnts[zero_mask].sum())
    right_cnt_data = int(cnts[right_mask].sum())

    nz = np.nonzero(dv > -KZERO_THRESHOLD)[0]
    left_cnt = int(nz[0]) if len(nz) else len(dv)

    bin_upper_bound: List[float] = []
    if left_cnt > 0:
        denom = total_sample_cnt - cnt_zero
        left_max_bin = int(left_cnt_data / max(denom, 1) * (max_bin - 1))
        left_max_bin = max(1, left_max_bin)
        bin_upper_bound = greedy_find_bin(dv[:left_cnt], cnts[:left_cnt],
                                          left_max_bin, left_cnt_data,
                                          min_data_in_bin)
        bin_upper_bound[-1] = -KZERO_THRESHOLD

    nz = np.nonzero(dv[left_cnt:] > KZERO_THRESHOLD)[0]
    right_start = left_cnt + int(nz[0]) if len(nz) else -1

    if right_start >= 0:
        right_max_bin = max_bin - 1 - len(bin_upper_bound)
        assert right_max_bin > 0
        right_bounds = greedy_find_bin(dv[right_start:], cnts[right_start:],
                                       right_max_bin, right_cnt_data,
                                       min_data_in_bin)
        bin_upper_bound.append(KZERO_THRESHOLD)
        bin_upper_bound.extend(right_bounds)
    else:
        bin_upper_bound.append(np.inf)
    return bin_upper_bound


class BinMapper:
    """Per-feature value->bin mapping (bin.h:61)."""

    def __init__(self):
        self.num_bin: int = 1
        self.missing_type: int = MissingType.NONE
        self.bin_type: int = BinType.NUMERICAL
        self.is_trivial: bool = True
        self.sparse_rate: float = 0.0
        self.bin_upper_bound: np.ndarray = np.array([np.inf])
        self.bin_2_categorical: List[int] = []
        self.categorical_2_bin: dict = {}
        self.min_val: float = 0.0
        self.max_val: float = 0.0
        self.default_bin: int = 0

    # -- construction -------------------------------------------------------

    def find_bin(self, values: np.ndarray, total_sample_cnt: int,
                 max_bin: int, min_data_in_bin: int, min_split_data: int,
                 bin_type: int = BinType.NUMERICAL, use_missing: bool = True,
                 zero_as_missing: bool = False) -> None:
        """BinMapper::FindBin (bin.cpp:208-365).

        ``values`` are the *sampled* non-trivial values; zeros are implied:
        total_sample_cnt - len(values) zeros (before NaN removal).
        """
        values = np.asarray(values, dtype=np.float64)
        num_sample_values = len(values)
        nan_mask = np.isnan(values)
        na_cnt = int(nan_mask.sum())
        values = values[~nan_mask]

        if not use_missing:
            self.missing_type = MissingType.NONE
        elif zero_as_missing:
            self.missing_type = MissingType.ZERO
        else:
            self.missing_type = (MissingType.NONE if na_cnt == 0
                                 else MissingType.NAN)
        if not use_missing:
            na_cnt = 0

        self.bin_type = bin_type
        self.default_bin = 0
        zero_cnt = int(total_sample_cnt - len(values) - na_cnt)

        # distinct values with zero spliced at its sorted position —
        # vectorized run-length grouping (the reference's sequential
        # CheckDoubleEqualOrdered chaining maps exactly onto runs of
        # consecutive ulp-near pairs; each run's representative is its
        # LAST value, matching the loop's distinct_values[-1] = cur)
        values = np.sort(values)
        if len(values):
            near = np.nextafter(values[:-1], np.inf) >= values[1:]
            starts = np.concatenate([[0], np.flatnonzero(~near) + 1])
            ends = np.concatenate([starts[1:], [len(values)]])
            dv = values[ends - 1].astype(np.float64)
            cnts = (ends - starts).astype(np.int64)
            # splice zero at its sorted position: the data is sorted, so
            # the negative->positive crossing (prev < 0 < cur in the
            # reference loop) happens at most once
            if values[0] > 0.0 and zero_cnt > 0:
                dv = np.insert(dv, 0, 0.0)
                cnts = np.insert(cnts, 0, zero_cnt)
            elif values[-1] < 0.0:
                if zero_cnt > 0:
                    dv = np.append(dv, 0.0)
                    cnts = np.append(cnts, zero_cnt)
            else:
                cross = np.flatnonzero((dv[:-1] < 0.0)
                                       & (values[starts[1:]] > 0.0))
                if len(cross):
                    pos = cross[0] + 1
                    dv = np.insert(dv, pos, 0.0)
                    cnts = np.insert(cnts, pos, zero_cnt)
        else:
            dv = np.array([0.0])
            cnts = np.array([zero_cnt], dtype=np.int64)

        self.min_val = float(dv[0])
        self.max_val = float(dv[-1])

        if bin_type == BinType.NUMERICAL:
            if self.missing_type == MissingType.ZERO:
                bounds = find_bin_with_zero_as_one_bin(
                    dv, cnts, max_bin, total_sample_cnt, min_data_in_bin)
                if len(bounds) == 2:
                    self.missing_type = MissingType.NONE
            elif self.missing_type == MissingType.NONE:
                bounds = find_bin_with_zero_as_one_bin(
                    dv, cnts, max_bin, total_sample_cnt, min_data_in_bin)
            else:
                bounds = find_bin_with_zero_as_one_bin(
                    dv, cnts, max_bin - 1, total_sample_cnt - na_cnt,
                    min_data_in_bin)
                bounds.append(np.nan)
            self.bin_upper_bound = np.array(bounds)
            self.num_bin = len(bounds)
            # default bin = bin containing 0.0
            self.default_bin = self._numerical_bin_for(0.0)
            cnt_in_bin = self._count_in_bins(dv, cnts, na_cnt)
        else:
            self._find_bin_categorical(dv, cnts, max_bin, total_sample_cnt,
                                       min_data_in_bin, na_cnt)
            cnt_in_bin = list(self._cat_cnt_in_bin)

        # trivial check (bin.cpp: num_bin <= 1 or one-sided filter)
        self.is_trivial = self.num_bin <= 1
        if not self.is_trivial and min_split_data > 0:
            self.is_trivial = self._need_filter(cnt_in_bin, total_sample_cnt,
                                                min_split_data)
        if total_sample_cnt > 0 and cnt_in_bin:
            self.sparse_rate = cnt_in_bin[self.default_bin] / total_sample_cnt

    def _numerical_bin_for(self, value: float) -> int:
        r = self.num_bin - 1
        if self.missing_type == MissingType.NAN:
            r -= 1
        bounds = self.bin_upper_bound[:r]
        return int(np.searchsorted(bounds, value, side="left"))

    def _count_in_bins(self, dv, cnts, na_cnt) -> List[int]:
        """Vectorized: bin of each distinct value = first bound >= v."""
        bounds = np.where(np.isnan(self.bin_upper_bound), np.inf,
                          self.bin_upper_bound)
        idx = np.searchsorted(bounds, dv, side="left")
        cnt_in_bin = np.bincount(idx, weights=np.asarray(cnts, np.float64),
                                 minlength=self.num_bin)
        cnt_in_bin = cnt_in_bin.astype(np.int64).tolist()
        if self.missing_type == MissingType.NAN:
            cnt_in_bin[self.num_bin - 1] = na_cnt
        return cnt_in_bin

    def _need_filter(self, cnt_in_bin, total_cnt, filter_cnt) -> bool:
        """NeedFilter (bin.cpp:44-73): no split point leaves filter_cnt on
        both sides -> feature is unusable."""
        if self.bin_type == BinType.NUMERICAL:
            sum_left = 0
            for i in range(self.num_bin - 1):
                sum_left += cnt_in_bin[i]
                if sum_left >= filter_cnt and total_cnt - sum_left >= filter_cnt:
                    return False
            return True
        else:
            if len(cnt_in_bin) <= 2:
                for i in range(len(cnt_in_bin) - 1):
                    sum_left = cnt_in_bin[i]
                    if sum_left >= filter_cnt and total_cnt - sum_left >= filter_cnt:
                        return False
                return True
            return False

    def _find_bin_categorical(self, dv, cnts, max_bin, total_sample_cnt,
                              min_data_in_bin, na_cnt) -> None:
        """Categorical branch of FindBin (bin.cpp:304-365)."""
        distinct_int: List[int] = []
        counts_int: List[int] = []
        for v, c in zip(dv, cnts):
            iv = int(v)
            if iv < 0:
                na_cnt += int(c)
                log.warning("Met negative value in categorical features, "
                            "will convert it to NaN")
            elif distinct_int and iv == distinct_int[-1]:
                counts_int[-1] += int(c)
            else:
                distinct_int.append(iv)
                counts_int.append(int(c))
        self.num_bin = 0
        self._cat_cnt_in_bin: List[int] = []
        rest_cnt = total_sample_cnt - na_cnt
        if rest_cnt > 0:
            if distinct_int and distinct_int[-1] // 100 > len(distinct_int):
                log.warning("Met categorical feature which contains sparse "
                            "values. Consider renumbering to consecutive "
                            "integers started from zero")
            order = np.argsort(-np.array(counts_int), kind="stable")
            counts_int = [counts_int[i] for i in order]
            distinct_int = [distinct_int[i] for i in order]
            if distinct_int and distinct_int[0] == 0:
                if len(counts_int) == 1:
                    counts_int.append(0)
                    distinct_int.append(distinct_int[0] + 1)
                counts_int[0], counts_int[1] = counts_int[1], counts_int[0]
                distinct_int[0], distinct_int[1] = distinct_int[1], distinct_int[0]
            cut_cnt = int((total_sample_cnt - na_cnt) * 0.99)
            self.bin_2_categorical = []
            self.categorical_2_bin = {}
            used_cnt = 0
            max_bin = min(len(distinct_int), max_bin)
            cur_cat = 0
            while (cur_cat < len(distinct_int)
                   and (used_cnt < cut_cnt or self.num_bin < max_bin)):
                if counts_int[cur_cat] < min_data_in_bin and cur_cat > 1:
                    break
                self.bin_2_categorical.append(distinct_int[cur_cat])
                self.categorical_2_bin[distinct_int[cur_cat]] = self.num_bin
                used_cnt += counts_int[cur_cat]
                self._cat_cnt_in_bin.append(counts_int[cur_cat])
                self.num_bin += 1
                cur_cat += 1
            if cur_cat == len(distinct_int) and na_cnt > 0:
                self.missing_type = MissingType.NAN
                self.num_bin += 1
                self._cat_cnt_in_bin.append(na_cnt)
            else:
                self.missing_type = MissingType.NONE
                if self.num_bin < len(distinct_int) or na_cnt > 0:
                    # leftover cats fall in the "other" last bin
                    leftover = (total_sample_cnt - na_cnt - used_cnt) + na_cnt
                    if self._cat_cnt_in_bin:
                        self._cat_cnt_in_bin[-1] += 0
            self.default_bin = 0

    # -- mapping ------------------------------------------------------------

    def value_to_bin(self, value):
        """Vectorized BinMapper::ValueToBin (bin.h:452-488)."""
        values = np.asarray(value, dtype=np.float64)
        scalar = values.ndim == 0
        values = np.atleast_1d(values)
        if self.bin_type == BinType.NUMERICAL:
            out = np.empty(values.shape, dtype=np.int32)
            nan_mask = np.isnan(values)
            v = np.where(nan_mask, 0.0, values)
            r = self.num_bin - 1
            if self.missing_type == MissingType.NAN:
                r -= 1
            # left bound binary search: first bin with value <= upper_bound
            out[:] = np.searchsorted(self.bin_upper_bound[:r], v, side="left")
            if self.missing_type == MissingType.NAN:
                out[nan_mask] = self.num_bin - 1
        else:
            out = np.full(values.shape, self.num_bin - 1, dtype=np.int32)
            iv = values.astype(np.int64, copy=False)
            iv = np.where(np.isnan(values), -1, iv)
            for cat, b in self.categorical_2_bin.items():
                out[iv == cat] = b
        return int(out[0]) if scalar else out

    def bin_to_value(self, bin_idx: int) -> float:
        """BinToValue (bin.h:109): numerical -> upper bound; cat -> category."""
        if self.bin_type == BinType.NUMERICAL:
            return float(self.bin_upper_bound[bin_idx])
        return float(self.bin_2_categorical[bin_idx])

    # -- serialization ------------------------------------------------------

    def feature_info(self) -> str:
        """String for the model header `feature_infos=` (dataset.cpp)."""
        if self.is_trivial:
            return "none"
        if self.bin_type == BinType.NUMERICAL:
            return f"[{self.min_val:g}:{self.max_val:g}]"
        return ":".join(str(c) for c in self.bin_2_categorical)

    def to_dict(self) -> dict:
        return {
            "num_bin": self.num_bin,
            "missing_type": self.missing_type,
            "bin_type": self.bin_type,
            "is_trivial": self.is_trivial,
            "sparse_rate": self.sparse_rate,
            "bin_upper_bound": self.bin_upper_bound.tolist(),
            "bin_2_categorical": list(self.bin_2_categorical),
            "min_val": self.min_val,
            "max_val": self.max_val,
            "default_bin": self.default_bin,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "BinMapper":
        m = cls()
        m.num_bin = d["num_bin"]
        m.missing_type = d["missing_type"]
        m.bin_type = d["bin_type"]
        m.is_trivial = d["is_trivial"]
        m.sparse_rate = d["sparse_rate"]
        m.bin_upper_bound = np.array(d["bin_upper_bound"], dtype=np.float64)
        m.bin_2_categorical = list(d["bin_2_categorical"])
        m.categorical_2_bin = {c: i for i, c in enumerate(m.bin_2_categorical)}
        m.min_val = d["min_val"]
        m.max_val = d["max_val"]
        m.default_bin = d["default_bin"]
        return m
