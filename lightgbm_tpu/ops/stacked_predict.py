"""Whole-model device prediction as one jitted scan of MXU matmuls.

The reference predicts by walking every tree per row under OpenMP
(src/boosting/gbdt_prediction.cpp:9-30, include/LightGBM/tree.h:212-266).
A pointer walk is the wrong shape for a TPU — data-dependent hops defeat
both the MXU and the vector unit. Instead the whole ensemble is lowered
to three dense contractions per tree chunk:

1.  Host-side, every feature's node thresholds become closed-right bin
    edges; raw rows are binned once (exact float64 searchsorted). Every
    node becomes a *decision table* over its feature's bins — built by
    evaluating the node's own host decision function (missing handling,
    default-left, categorical bitsets: tree.h:183-201) at one
    representative value per bin, so the device path agrees with the
    host path by construction.
2.  ``C[n, s] = OH @ W`` — an int8 one-hot matmul looks up every node
    decision for every row at the int8 MXU rate.
3.  A per-tree batched einsum against the signed ancestor matrix
    ``P[t, s, l]`` (+1 = leaf l sits in s's left subtree, -1 = right)
    counts how many ancestor decisions point at each leaf; the row's
    leaf is the one whose count equals its depth. One more einsum with
    the leaf values accumulates per-class scores.

No gathers, no per-tree dispatch: a 500-tree model predicts in one
host->device upload per row chunk and ~T/TC fused scan steps.

Serving shape (ops/predict_cache.py): the dispatch is a pure function
of an explicit geometry key held in a process-wide registry, online
micro-batches pad to power-of-two serve buckets (bit-exact — rows are
independent in every kernel here and pad rows are sliced off), and
appending trees to an already-stacked model re-stacks ONLY the new
tree chunk (``extend``): a new threshold splits an existing bin into
sub-bins on which every OLD node's decision is constant (its own
threshold is a bin edge), so old decision-table rows are copied, not
re-evaluated.

Numerical note: leaf values and per-row score accumulation run in
float32 on device (the reference accumulates in double,
gbdt_prediction.cpp). Expect ~1e-7 RELATIVE error that grows with
leaf-value magnitude and tree count; for parity-sensitive comparisons
against the reference at f64 resolution, use the host prediction path
(``use_pallas=False`` routes chunks through the same f32 kernels —
the exact-f64 path is the per-tree host traversal, models/tree.py).
"""
from __future__ import annotations

import functools
from functools import partial
from typing import List, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import autotune, predict_cache
from ..io.binning import MissingType
from ..obs import reqlog
from ..utils import log, timing

# decision_type bit layout (models/tree.py, mirroring tree.h)
K_CATEGORICAL_MASK = 1
K_DEFAULT_LEFT_MASK = 2

_ZERO_EPS = 1e-35
# per-feature table-width cap: categorical features whose bitsets cover
# more distinct categories than this fall back to the host path
MAX_FEATURE_WIDTH = 1024
TREE_CHUNK = 16    # trees per scan/grid step (TC=16 measured ~10%
                   # faster than 8 at the 500-tree bench shape; wide
                   # models drop TC until the kernel blocks fit VMEM)
# fused-kernel working-set budget (shared with the autotuner, which
# prices the SAME block shapes the kernel's BlockSpecs are built from:
# ops/autotune.py forest_block_shapes / forest_vmem_bytes)
_PALLAS_VMEM_BUDGET = autotune.PALLAS_VMEM_BUDGET_BYTES


class StackedModel:
    """Host-built stacked arrays for a list of trees + the jitted runner.

    ``serve_bucket`` is the owning booster's ``tpu_serve_bucket`` policy
    (None = the process default installed by predict_cache.configure)."""

    def __init__(self, trees: List, num_features: int, num_class: int,
                 serve_bucket: Optional[int] = None):
        self.num_class = num_class
        self.num_trees = len(trees)
        self._serve_policy = serve_bucket
        self.ok = True
        try:
            self._build(trees, num_features)
        except _FallbackError as e:
            log.warning("stacked predict unavailable (%s); "
                        "host prediction path will be used", e)
            self.ok = False

    # -- host-side build ----------------------------------------------------

    def _build(self, trees: List, num_features: int) -> None:
        F = num_features
        self._F = F
        L = max([t.num_leaves for t in trees] + [2])
        S = L - 1

        # 1. per-feature edges / category sets from every node
        self._thr_sets: List[set] = [set() for _ in range(F)]
        self._cat_sets: List[set] = [set() for _ in range(F)]
        self._zero_mt = np.zeros(F, bool)
        self._is_cat = np.zeros(F, bool)
        self._scan_nodes(trees)

        # 2. per-feature representative values + binning data
        reps = self._rebuild_tables()

        # 3. decision tables, ancestor matrix, targets, leaf values
        W, P, tgt, leaf_val = self._stack_trees(trees, reps, S, L)
        if W.nbytes > (2 << 30):
            raise _FallbackError(f"W matrix {W.nbytes >> 20} MB")
        self._W_host = W
        self._P_host = P
        self._tgt_host = tgt
        self._leaf_host = leaf_val
        self._S, self._L = S, L
        self._dev_cache: dict = {}
        self._dispatch_memo: dict = {}
        predict_cache.count_stack(len(trees))

    def _scan_nodes(self, trees: List) -> None:
        """Accumulate every node's thresholds / category bitsets into
        the per-feature sets (the union layout the decision tables are
        binned against). Raises on shapes the stacker cannot host."""
        F = self._F
        for t in trees:
            for s in range(t.num_leaves - 1):
                f = t.split_feature[s]
                if f >= F:
                    raise _FallbackError(f"node feature {f} >= {F}")
                dt = t.decision_type[s]
                if dt & K_CATEGORICAL_MASK:
                    self._is_cat[f] = True
                    ci = t.threshold_in_bin[s]
                    lo, hi = t.cat_boundaries[ci], t.cat_boundaries[ci + 1]
                    for wi in range(lo, hi):
                        w = int(t.cat_threshold[wi]) & 0xFFFFFFFF
                        base = (wi - lo) * 32
                        while w:
                            b = (w & -w).bit_length() - 1
                            self._cat_sets[f].add(base + b)
                            w &= w - 1
                else:
                    self._thr_sets[f].add(float(t.threshold[s]))
                    if (dt >> 2) & 3 == MissingType.ZERO:
                        self._zero_mt[f] = True
        if np.any(self._is_cat & (np.array(
                [len(s) for s in self._thr_sets]) > 0)):
            raise _FallbackError("feature used both numerically and "
                                 "categorically")

    def _rebuild_tables(self) -> List[np.ndarray]:
        """Per-feature representative values, bin edges, table offsets
        and the device-binning fast-path arrays, all derived from the
        accumulated threshold/category sets. Returns the rep list.

        Numerical layout: [m closed-right bins][overflow][NaN].
        Categorical layout: [known cats][other][negative/NaN]."""
        F = self._F
        self._edges: List[Optional[np.ndarray]] = [None] * F
        self._cats: List[Optional[np.ndarray]] = [None] * F
        reps: List[np.ndarray] = []
        widths = np.zeros(F, np.int64)
        for f in range(F):
            if self._is_cat[f]:
                cs = np.array(sorted(self._cat_sets[f]), np.float64)
                if cs.size > MAX_FEATURE_WIDTH:
                    raise _FallbackError(
                        f"categorical feature {f} has {cs.size} "
                        f"distinct categories (> {MAX_FEATURE_WIDTH})")
                self._cats[f] = cs
                other = (cs.max() + 1.0) if cs.size else 1.0
                rep = np.concatenate([cs, [other, -1.0]])
            else:
                thr = set(self._thr_sets[f])
                if self._zero_mt[f]:
                    # isolate the reference's zero band |x| <= 1e-35
                    # (tree.h:188) into its own bin so a representative
                    # speaks for every value it covers
                    thr |= {np.nextafter(-_ZERO_EPS, -np.inf), _ZERO_EPS}
                edges = np.asarray(sorted(thr), np.float64)
                if edges.size > MAX_FEATURE_WIDTH:
                    raise _FallbackError(
                        f"feature {f} has {edges.size} thresholds")
                self._edges[f] = edges
                over = (np.nextafter(edges[-1], np.inf)
                        if edges.size else 0.0)
                rep = np.concatenate([edges, [over, np.nan]])
            # widths bucketed to 32 (8-aligned sublane starts are a
            # Mosaic requirement; the coarser bucket makes the kernel
            # SHAPE stable across models — e.g. every max_bin=63
            # feature lands on width 64 — so the predict registry and
            # persistent compile cache serve repeat predicts instead of
            # a fresh ~40 s Mosaic compile per model). Padded slots
            # have all-zero W rows and are never addressed by a code.
            widths[f] = -(-rep.size // 32) * 32
            reps.append(rep)
        self._rep_sizes = np.array([r.size for r in reps], np.int64)
        self._offsets = np.concatenate([[0], np.cumsum(widths)])
        self._Wtot = int(self._offsets[-1])

        # device-binning fast path (numerical features only): f32 edges
        # rounded DOWN so an f32 row compares exactly like f64 against
        # the f64 threshold (x <= t  <=>  x <= largest-f32 <= t, for
        # f32-representable x)
        self._dev_bin_ok = not any(c is not None for c in self._cats)
        if self._dev_bin_ok:
            m_max = max((e.size for e in self._edges if e is not None),
                        default=0)
            E = np.full((F, max(m_max, 1)), np.inf, np.float32)
            for f in range(F):
                e = self._edges[f]
                if e is None or e.size == 0:
                    continue
                # clip into f32 range BEFORE the cast: thresholds near
                # ±DBL_MAX would otherwise overflow to ±inf with a
                # RuntimeWarning. The clipped edge keeps the compare
                # semantics: any finite f32 x <= f32max < huge-t (left
                # stays left), and the bump below handles the negative
                # side exactly like any other not-f32-representable edge
                f32i = np.finfo(np.float32)
                ef = e.clip(f32i.min, f32i.max).astype(np.float32)
                bump = ef.astype(np.float64) > e
                ef[bump] = np.nextafter(ef[bump], -np.inf)
                E[f, :e.size] = ef
            self._E_f32 = E
            self._nan_slot = np.array(
                [self._offsets[f] + self._rep_sizes[f] - 1
                 for f in range(F)],
                np.int32)
            self._off32 = self._offsets[:F].astype(np.int32)
        return reps

    def _stack_trees(self, trees: List, reps: List[np.ndarray],
                     S: int, L: int):
        """Decision tables / ancestor matrices / leaf values for
        ``trees`` against the CURRENT table layout — called with the
        full ensemble at build and with only the appended chunk on an
        incremental ``extend``."""
        T = len(trees)
        Wtot = self._Wtot
        W = np.zeros((Wtot, T, S), np.int8)
        P = np.zeros((T, S, L), np.int8)
        tgt = np.full((T, L), 1e9, np.float32)   # padded leaves: no match
        leaf_val = np.zeros((T, L), np.float32)
        for ti, t in enumerate(trees):
            nl = t.num_leaves
            leaf_val[ti, :nl] = np.asarray(t.leaf_value[:nl], np.float32)
            for s in range(nl - 1):
                f = t.split_feature[s]
                o = self._offsets[f]
                W[o:o + self._rep_sizes[f], ti, s] = _node_table(
                    t, s, reps[f])
            # DFS: signed ancestor matrix + per-leaf left-count target
            if nl == 1:
                tgt[ti, 0] = 0.0
                continue
            stack2 = [(0, [])]           # node, ancestor (node, sign) list
            while stack2:
                node, anc = stack2.pop()
                for child, sign in ((t.left_child[node], 1),
                                    (t.right_child[node], -1)):
                    a2 = anc + [(node, sign)]
                    if child < 0:
                        lf = ~child
                        # E = (#left-ancestors gone left)
                        #   - (#right-ancestors gone left) == nLeft
                        # exactly when every ancestor decision points
                        # at this leaf
                        tgt[ti, lf] = sum(1 for _, sg in a2 if sg > 0)
                        for sn, sg in a2:
                            P[ti, sn, lf] = sg
                    else:
                        stack2.append((child, a2))
        return W, P, tgt, leaf_val

    # -- incremental stacking -----------------------------------------------

    def clone_for_extend(self) -> "StackedModel":
        """A shallow copy whose ``extend()`` cannot perturb a reader
        of the original — the copy-on-write half of the serving lock's
        publish protocol (models/gbdt.py _stacked_model): a predict()
        in flight on the ORIGINAL keeps a fully consistent model while
        the training thread extends the clone and publishes it.

        Only the containers ``extend`` mutates IN PLACE are duplicated
        (threshold/category sets, the role masks, the device-stack and
        dispatch memos — whose ``clear()`` would otherwise nuke the
        original's too); the big host tables and binning arrays are
        only ever REASSIGNED by extend, so sharing them until then is
        safe."""
        import copy
        new = copy.copy(self)
        new._thr_sets = [set(s) for s in self._thr_sets]
        new._cat_sets = [set(s) for s in self._cat_sets]
        new._zero_mt = self._zero_mt.copy()
        new._is_cat = self._is_cat.copy()
        new._dev_cache = dict(self._dev_cache)
        new._dispatch_memo = dict(self._dispatch_memo)
        return new

    def extend(self, new_trees: List) -> bool:
        """Append ``new_trees``, re-stacking ONLY the new tree chunk.

        Soundness of copying the old decision-table rows instead of
        re-evaluating every old node: a new threshold splits an
        existing bin into sub-bins that each lie WHOLLY inside the old
        bin, and an old node's decision is constant across any old bin
        (its own threshold is one of the bin edges; for zero-as-missing
        nodes the ±1e-35 band is an isolated bin whose sub-bins stay
        inside the band). New categories map to the old "other" slot —
        exactly the decision every old bitset gives them. So
        ``W_new[new_slot, old_trees] = W_old[old_code(new_rep)]`` where
        ``old_code`` is the ORIGINAL binning of the new representative
        values — the same function rows are binned with at predict.

        Returns False when the extension cannot be hosted (feature-role
        conflict, width cap, byte cap) — the caller falls back to a
        full rebuild, which will surface the same fallback if it is
        structural. The model is untouched on failure."""
        new_trees = list(new_trees)
        if not self.ok:
            return False
        if not new_trees:
            return True
        # snapshot everything the trial mutates, so a mid-flight
        # fallback restores the model exactly
        saved = ([set(s) for s in self._thr_sets],
                 [set(s) for s in self._cat_sets],
                 self._zero_mt.copy(), self._is_cat.copy(),
                 self._edges, self._cats, self._rep_sizes,
                 self._offsets, self._Wtot, self._dev_bin_ok,
                 getattr(self, "_E_f32", None),
                 getattr(self, "_nan_slot", None),
                 getattr(self, "_off32", None))
        old_edges, old_cats = self._edges, self._cats
        old_offsets = self._offsets
        S_old, L_old = self._S, self._L
        T_old = self.num_trees
        try:
            self._scan_nodes(new_trees)
            reps = self._rebuild_tables()
            L = max([L_old] + [t.num_leaves for t in new_trees])
            S = L - 1
            # old tables re-laid into the new slot layout: one fancy-
            # index copy per ensemble, no node re-evaluation
            W = np.zeros((self._Wtot, T_old + len(new_trees), S),
                         np.int8)
            for f in range(self._F):
                o_new = self._offsets[f]
                n_new = int(self._rep_sizes[f])
                src = _feature_codes(reps[f], old_edges[f], old_cats[f])
                W[o_new:o_new + n_new, :T_old, :S_old] = \
                    self._W_host[old_offsets[f] + src, :, :]
            Wn, Pn, tgtn, leafn = self._stack_trees(new_trees, reps,
                                                    S, L)
            if W.nbytes > (2 << 30):
                raise _FallbackError(f"W matrix {W.nbytes >> 20} MB")
            W[:, T_old:, :] = Wn
            P = np.concatenate([
                np.pad(self._P_host,
                       ((0, 0), (0, S - S_old), (0, L - L_old))), Pn])
            tgt = np.concatenate([
                np.pad(self._tgt_host, ((0, 0), (0, L - L_old)),
                       constant_values=1e9), tgtn])
            leaf = np.concatenate([
                np.pad(self._leaf_host, ((0, 0), (0, L - L_old))),
                leafn])
        except _FallbackError as e:
            # full restore — including the f32 edge planes, which a
            # SUCCESSFUL _rebuild_tables overwrites before a later
            # check (the W byte cap) can still raise
            (self._thr_sets, self._cat_sets, self._zero_mt,
             self._is_cat, self._edges, self._cats, self._rep_sizes,
             self._offsets, self._Wtot, self._dev_bin_ok,
             self._E_f32, self._nan_slot, self._off32) = saved
            log.info("incremental stack fell back (%s); rebuilding", e)
            return False
        self._W_host, self._P_host = W, P
        self._tgt_host, self._leaf_host = tgt, leaf
        self._S, self._L = S, L
        self.num_trees = T_old + len(new_trees)
        # stale device stacks / dispatch wrappers key off the old
        # geometry — drop them (uploads re-issue lazily per tree range)
        self._dev_cache.clear()
        self._dispatch_memo.clear()
        predict_cache.count_extend(len(new_trees))
        return True

    # -- prediction ---------------------------------------------------------

    def _bin_rows(self, X: np.ndarray) -> np.ndarray:
        """[N, F] float64 -> global one-hot column codes [N, Fm] int32
        (model features only; surplus input columns are ignored)."""
        N = X.shape[0]
        Fm = len(self._offsets) - 1
        codes = np.zeros((N, Fm), np.int32)
        nanc = np.full(N, np.nan)
        for f in range(Fm):
            x = X[:, f] if f < X.shape[1] else nanc
            codes[:, f] = self._offsets[f] + _feature_codes(
                x, self._edges[f], self._cats[f])
        return codes

    def _stack_range(self, key, first: int, ntree: int, Sp: int,
                     Lp: int, tgt_dtype, TC: int):
        """Shared stacker for the scan (Sp=S, Lp=L) and Pallas
        (MXU-tile-padded) layouts: slice the tree range, pad to a TC
        multiple, and shape [steps, ...] chunk stacks."""
        hit = self._dev_cache.get(key)
        if hit is not None:
            return hit
        # bounded: a learning-curve loop (predict at 10, 20, ... trees)
        # would otherwise pin one device copy of W/P per tree range
        while len(self._dev_cache) >= 4:
            self._dev_cache.pop(next(iter(self._dev_cache)))
        TC = min(TC, max(ntree - first, 1))
        nt = ntree - first
        steps = -(-nt // TC)
        pad = steps * TC - nt
        S, L = self._S, self._L
        sl = slice(first, ntree)

        def padT(a, fill=0.0):
            a = a[sl]
            if pad:
                shape = (pad,) + a.shape[1:]
                a = np.concatenate(
                    [a, np.full(shape, fill, a.dtype)], axis=0)
            return a

        W = np.transpose(self._W_host, (1, 0, 2))[sl]       # [nt, Wtot, S]
        if pad:
            W = np.concatenate(
                [W, np.zeros((pad,) + W.shape[1:], np.int8)])
        W = np.pad(W, ((0, 0), (0, 0), (0, Sp - S)))
        W = (W.reshape(steps, TC, self._Wtot, Sp)
              .transpose(0, 2, 1, 3)
              .reshape(steps, self._Wtot, TC * Sp))
        P = np.pad(padT(self._P_host),
                   ((0, 0), (0, Sp - S), (0, Lp - L)))
        P = P.reshape(steps, TC, Sp, Lp)
        tgt = np.pad(padT(self._tgt_host, 1e9).astype(np.float64),
                     ((0, 0), (0, Lp - L)), constant_values=1e9)
        if tgt_dtype == np.int32:
            tgt = np.minimum(tgt, 2 ** 30)
        tgt = tgt.astype(tgt_dtype).reshape(steps, TC, Lp)
        leaf = np.pad(padT(self._leaf_host),
                      ((0, 0), (0, Lp - L))).reshape(steps, TC, Lp)
        cls = (np.arange(first, first + steps * TC) % self.num_class)
        clsOH = np.eye(self.num_class, dtype=np.float32)[cls].reshape(
            steps, TC, self.num_class)
        if pad:   # padded trees: no leaf ever matches, but zero the class
            clsOH[-1, TC - pad:, :] = 0.0
        out = (jnp.asarray(W), jnp.asarray(P.astype(np.int8)),
               jnp.asarray(tgt), jnp.asarray(leaf), jnp.asarray(clsOH))
        self._dev_cache[key] = out
        return out

    def _tree_chunk(self) -> int:
        """Trees per scan step (XLA path): halved for wide models so the
        intermediate C matrix stays reasonable."""
        return TREE_CHUNK if self._Wtot <= 4096 else TREE_CHUNK // 2

    def _pallas_tc(self, row_tile: int = autotune.DEFAULT_ROW_TILE
                   ) -> Optional[int]:
        """Trees per grid step for the fused forest kernel, sized from
        the kernel's ACTUAL VMEM blocks (not just Wtot): the
        double-buffered W ([Wtot, TC*Sp] int8) and P ([TC, Sp, Lp] int8)
        inputs plus the in-kernel C/one-hot temporaries all scale with
        TC and the 128-padded S/L, so a large-num_leaves model can blow
        the budget at a modest Wtot. The byte estimate is
        autotune.forest_vmem_bytes — priced from the SAME block shapes
        forest_predict_pallas builds its BlockSpecs from. Returns None
        when even TC=1 does not fit — predict() then routes to the XLA
        scan path instead of tripping a Mosaic compile error on
        device."""
        Sp = -(-self._S // 128) * 128
        Lp = -(-self._L // 128) * 128
        # K/F default for skeleton callers (tests size the guard with
        # only _S/_L/_Wtot set); both terms are minor
        K = max(getattr(self, "num_class", 1), 1)
        offs = getattr(self, "_offsets", None)
        F = max(len(offs) - 1, 0) if offs is not None else 0
        tc = TREE_CHUNK
        while tc >= 1:
            est = autotune.forest_vmem_bytes(
                F=F, Wtot=self._Wtot, TC=tc, Sp=Sp, Lp=Lp, K=K,
                row_tile=row_tile)
            if est <= _PALLAS_VMEM_BUDGET:
                return tc
            tc //= 2
        return None

    def _device_arrays(self, first: int, ntree: int):
        return self._stack_range((first, ntree), first, ntree,
                                 self._S, self._L, np.float32,
                                 self._tree_chunk())

    def _dispatch(self, key: tuple, builder):
        """Registry-backed dispatch memo: the process registry is
        consulted ONCE per (model, geometry) — so its hit/miss counts
        measure CROSS-model reuse (the retrain case), not per-chunk
        call traffic."""
        fn = self._dispatch_memo.get(key)
        if fn is None:
            # jit-capture: ok(builder) — forwarding seam: the real
            # builders are audited at their _dispatch call sites
            fn = predict_cache.get(key, builder)
            self._dispatch_memo[key] = fn
        return fn

    def _stream(self, rows, N: int, chunk: int, prep_layout, runner):
        """Host prep (slice + pad-to-bucket + layout) for each row
        chunk on the ingest prefetch worker (io/ingest.py), device
        dispatch as chunks arrive, ordered async handles returned —
        chunk k's d2h overlaps chunk k+1's prep and compute. A single
        chunk skips the worker thread entirely (online micro-batches
        must not pay a thread spawn per request)."""

        def prep(c0):
            part = rows[c0:c0 + chunk]
            nrows = part.shape[0]
            if nrows < chunk:
                # pad to the full bucket shape so every chunk reuses
                # one compiled program (padded rows produce garbage
                # scores/leaves, sliced off by the caller)
                part = np.concatenate([part, np.zeros(
                    (chunk - nrows,) + part.shape[1:], part.dtype)])
            return prep_layout(part), nrows

        if N <= chunk:
            parts = [prep(0)]
        else:
            from ..io.ingest import prefetch
            parts = prefetch((lambda c0=c0: prep(c0))
                             for c0 in range(0, N, chunk))
        return [(runner(part), nrows) for part, nrows in parts]

    def warmup(self, rows: int = 1) -> bool:
        """Run one throwaway predict over ``rows`` zero rows so the
        device stacks upload and the serve-bucket program for this
        batch shape compiles NOW, not on the first live request — the
        publish seam of a retrain-while-serve swap (lrb.py) calls this
        on the trainer thread before the new model goes live, so the
        post-swap request stream never pays the cold tail. A
        same-geometry predecessor makes this a registry hit
        (ops/predict_cache.py) and the cost is one warm dispatch."""
        if not self.ok:
            return False
        self.predict(np.zeros((max(int(rows), 1), self._F),
                              np.float64))
        return True

    def predict(self, X: np.ndarray, first: int = 0,
                ntree: Optional[int] = None,
                pred_leaf: bool = False,
                row_chunk: int = 262144,
                use_pallas: Optional[bool] = None) -> np.ndarray:
        """Raw scores [K, N] (or leaf indices [N, ntree-first] int32)."""
        ntree = self.num_trees if ntree is None else min(ntree,
                                                         self.num_trees)
        X = np.ascontiguousarray(np.asarray(X, np.float64))
        Fm = len(self._offsets) - 1
        # device binning when rows are f32-exact and all-numerical:
        # skips the host searchsorted pass AND halves the upload.
        # Probe a small sample first so ineligible inputs (true f64
        # data) don't pay a full-matrix round-trip scan.
        dev_bin = self._dev_bin_ok and X.shape[1] >= Fm
        rows = None
        # overflow in these casts is EXPECTED for not-f32-exact data
        # (values beyond f32 range become inf, _f32_exact rejects them
        # and the host binning path runs) — don't warn about it
        with np.errstate(over="ignore"):
            if dev_bin:
                probe = X[:64, :Fm]
                dev_bin = _f32_exact(probe, probe.astype(np.float32))
            if dev_bin:
                Xf = X[:, :Fm].astype(np.float32)
                dev_bin = _f32_exact(X[:, :Fm], Xf)
                rows = Xf if dev_bin else None
        if rows is None:
            rows = self._bin_rows(X)
        N = X.shape[0]
        from ..utils.device import backend_kind
        bk = backend_kind()
        # route by device kind: TPU always takes the fused kernel, GPU
        # takes its Triton twin when the lowering is importable, CPU
        # runs the XLA scan (use_pallas forces the kernel either way —
        # off-accelerator it runs in interpret mode, which is how the
        # tier-1 parity suite drives it)
        if use_pallas is not None:
            forest = bool(use_pallas)
        else:
            forest = (bk == "tpu" or (bk == "gpu"
                                      and autotune.gpu_pallas_supported()))
        gpu_route = (forest and bk == "gpu"
                     and autotune.gpu_pallas_supported())
        # VMEM guard from the kernel's ACTUAL block bytes (W, P, C,
        # one-hot all scale with TC x padded S/L, not just Wtot):
        # _pallas_tc halves the tree chunk until the blocks fit and
        # returns None for models that cannot fit at all — those use
        # the XLA scan path instead of crashing the fused kernel.
        tc = self._pallas_tc() if forest else None
        row_tile = (autotune.DEFAULT_GPU_ROW_TILE if gpu_route
                    else autotune.DEFAULT_ROW_TILE)
        if forest and tc is None:
            # the default row tile can miss the VMEM budget where a
            # smaller one fits (row_tile-scaled blocks dominating at
            # large Wtot/Sp) — try the smaller candidate tiles before
            # surrendering to the XLA scan path
            for rt in (1024, 512):
                tc = self._pallas_tc(rt)
                if tc is not None:
                    row_tile = rt
                    break
        forest = forest and tc is not None
        offs = tuple(int(o) for o in self._offsets)
        m_max = self._E_f32.shape[1] if dev_bin else 0
        device = autotune.device_kind()
        if forest and not pred_leaf:
            # fused forest kernel, dispatched per ROW CHUNK: every
            # chunk's [chunk, K] f32 result is queued asynchronously,
            # so the per-chunk downloads overlap the remaining chunks'
            # compute — on an RPC-tunneled device the transfer wall
            # otherwise serializes after the math. f32 on the wire
            # (f64 only at this API boundary, predictor.hpp-style)
            # halves the download.
            interp = not (bk == "tpu" or gpu_route)
            row_tile, tc = self._tuned_tiles(first, ntree, row_tile,
                                             tc, interp,
                                             gpu_route=gpu_route)
            dev = self._device_arrays_pallas(first, ntree, tc)
            fchunk = 1 << 18
            # online batches pad to a pow2 serve bucket so request
            # sizes 1..bucket share ONE trace (the kernel pads rows to
            # a row_tile multiple internally either way — bucketing
            # only stabilizes the jit key)
            chunk = (fchunk if N > fchunk else min(
                fchunk, predict_cache.serve_bucket_rows(
                    N, self._serve_policy)))
            # the request context records the width ACTUALLY
            # dispatched — the clamp above can shrink the raw
            # serve-bucket answer for huge batches (obs/reqlog.py)
            reqlog.note_bucket(chunk)
            _, TCr, Sp, Lp = dev[1].shape
            key = ("pallas-gpu" if gpu_route else "pallas", device,
                   offs, Sp, Lp, self.num_class,
                   TCr, dev[0].shape[0], row_tile, dev_bin, m_max,
                   chunk, interp)

            # the registered dispatch is PURE in the key: the model's
            # device stacks (and edge tables) arrive as arguments, so
            # a registry hit from a retrained same-geometry model runs
            # the warm program on ITS arrays
            def build():
                if dev_bin:
                    fx = (forest_predict_from_x_gpu if gpu_route
                          else forest_predict_from_x)

                    def run(part, dv, aux):
                        return fx(
                            jnp.asarray(part), *aux, *dv,
                            offsets=offs, row_tile=row_tile,
                            interpret=interp)
                else:
                    fp = (forest_predict_pallas_gpu if gpu_route
                          else forest_predict_pallas)

                    def run(part, dv, aux):
                        return fp(
                            jnp.asarray(part), *dv, offsets=offs,
                            row_tile=row_tile, interpret=interp)
                return run

            aux = ()
            if dev_bin:     # upload the edge tables once, not per chunk
                aux = (jnp.asarray(self._E_f32),
                       jnp.asarray(self._off32),
                       jnp.asarray(self._nan_slot))
            fn = self._dispatch(key, build)
            # host half of the double buffer (io/ingest.py prefetch):
            # the worker slices/pads/transposes chunk k+1 while the
            # device chews on chunk k
            layout = ((lambda p: p) if dev_bin
                      else (lambda p: np.ascontiguousarray(p.T)))
            handles = self._stream(rows, N, chunk, layout,
                                   lambda part: fn(part, dev, aux))
            acc = np.concatenate(
                [np.asarray(h)[:nr] for h, nr in handles], axis=0)
            return acc.T.astype(np.float64)
        dev = self._device_arrays(first, ntree)
        # pad rows to a power-of-two serve bucket so repeated odd-sized
        # calls (an online request stream) reuse one compiled kernel
        # per bucket instead of recompiling per batch size — bit-exact,
        # rows are independent and the pad is sliced off below. Policy
        # knob: tpu_serve_bucket (ops/predict_cache.py).
        bucket = min(row_chunk, predict_cache.serve_bucket_rows(
            N, self._serve_policy))
        # record the clamped width the batch actually rides (the raw
        # serve-bucket answer noted inside serve_bucket_rows can
        # exceed row_chunk for huge batches)
        reqlog.note_bucket(bucket)
        TC = dev[1].shape[1]
        key = ("scan", device, offs, self._S, self._L, self.num_class,
               TC, dev[0].shape[0], bool(pred_leaf), dev_bin, m_max,
               bucket)
        Wtot = self._Wtot

        # pure in the key (see the pallas path note): stacks/edge
        # tables are arguments, not closure state
        def build():
            if dev_bin:
                def run(chunk, dv, aux):
                    return _run_chunk_from_x(
                        jnp.asarray(chunk), *aux, *dv, Wtot, pred_leaf)
            else:
                def run(chunk, dv, aux):
                    return _run_chunk(jnp.asarray(chunk), *dv,
                                      Wtot, pred_leaf)
            return run

        aux = ()
        if dev_bin:     # upload the edge tables once, not per chunk
            aux = (jnp.asarray(self._E_f32), jnp.asarray(self._off32),
                   jnp.asarray(self._nan_slot))
        # jit-capture: ok(Wtot) — determined by offs (the per-feature
        # table offsets sum to Wtot), which IS in the key
        fn = self._dispatch(key, build)
        handles = self._stream(rows, N, bucket, lambda p: p,
                               lambda p: fn(p, dev, aux))
        if pred_leaf:
            out = np.concatenate(
                [np.asarray(h)[:nr] for h, nr in handles], axis=0)
            return out[:, :ntree - first]
        return np.concatenate(
            [np.asarray(h)[:nr] for h, nr in handles],
            axis=0).T.astype(np.float64)

    def _device_arrays_pallas(self, first: int, ntree: int, tc: int):
        """Kernel-shaped stacks: per-tree axes padded to MXU tiles
        (S -> Sp multiple of 128 so per-tree lane slices of C are
        aligned; L -> Lp for the second dot's output lanes)."""
        Sp = -(-self._S // 128) * 128
        Lp = -(-self._L // 128) * 128
        return self._stack_range(("pallas", first, ntree, tc), first,
                                 ntree, Sp, Lp, np.int32, tc)

    def _tuned_tiles(self, first: int, ntree: int, rt_default: int,
                     tc_default: int, interp: bool,
                     gpu_route: bool = False):
        """(row_tile, tc) for the fused forest kernel — autotuned on
        first encounter of this model-shape key (ops/autotune.py),
        cached on disk thereafter. The key is the kernel's SHAPE — the
        exact table width Wtot (already a sum of 32-bucketed
        per-feature widths, so retrained models of one pipeline
        usually land on the same value), padded S/L, classes, device
        kind — not the tree count: timing scales uniformly in the step
        count, so the ranking measured on the first model of a shape
        serves all of them. A cached choice is applied only when it is
        in THIS model's freshly computed candidate set, so an entry
        from a near-miss shape can never install a tc that does not
        fit. Off-TPU and with tpu_autotune=off the measured default
        tile is used untouched."""
        t = autotune.tuner()
        if interp or t.mode == "off":
            return rt_default, tc_default
        if gpu_route:
            # per-CTA row tiles: far smaller than the TPU grid tiles —
            # the register-resident accumulator and the F gather rows
            # scale with the tile, not a VMEM double buffer
            tiles = ((256, 512, 1024, 2048)
                     if t.mode == "exhaustive" else (512, 1024, 2048))
        else:
            tiles = ((512, 1024, 2048, 4096, 8192)
                     if t.mode == "exhaustive" else (1024, 2048, 4096))
        cands = []
        for rt in tiles:
            tc = self._pallas_tc(rt)
            if tc is not None:
                cands.append({"row_tile": rt, "tc": tc})
        if not cands:
            return rt_default, tc_default
        Sp = -(-self._S // 128) * 128
        Lp = -(-self._L // 128) * 128
        key = {"Wtot": self._Wtot, "Sp": Sp, "Lp": Lp,
               "K": self.num_class, "F": len(self._offsets) - 1,
               "device": autotune.device_kind(),
               # candidate fingerprint (Autotuner.best contract): the
               # feasible (row_tile, tc) set varies with the tuning
               # mode and model geometry, and on/exhaustive runs must
               # not thrash or shadow each other's entries
               "tiles": [[c["row_tile"], c["tc"]] for c in cands]}
        offs = tuple(int(o) for o in self._offsets)
        # a multiple of every tile, several steps above the largest
        # one: a max(tiles)-row dispatch would amortize fixed per-
        # dispatch overhead over ONE grid step for the biggest tile
        # but several for the small ones, biasing the ranking toward
        # big tiles relative to the real 2^18-row predict chunks
        n_meas = min(8 * max(tiles), 1 << 18)
        codes = jnp.zeros((len(offs) - 1, n_meas), jnp.int32)

        def measure(cand):
            dev = self._device_arrays_pallas(first, ntree, cand["tc"])
            fp = (forest_predict_pallas_gpu if gpu_route
                  else forest_predict_pallas)
            return timing.measure(
                lambda: fp(
                    codes, *dev, offsets=offs,
                    row_tile=cand["row_tile"], interpret=False))

        choice = t.best(
            "forest", key, cands, measure,
            default={"row_tile": rt_default, "tc": tc_default})
        rt, tc = int(choice["row_tile"]), int(choice["tc"])
        # losing candidates' device stacks would otherwise sit in the
        # (bounded) _dev_cache; keep only the winner's
        for k in [k for k in self._dev_cache
                  if k[0] == "pallas" and k[3] != tc]:
            self._dev_cache.pop(k, None)
        return rt, tc


class _FallbackError(Exception):
    pass


def _feature_codes(x: np.ndarray, edges: Optional[np.ndarray],
                   cats: Optional[np.ndarray]) -> np.ndarray:
    """Values -> LOCAL bin codes for one feature under the table
    layout of _rebuild_tables. Shared between row binning (_bin_rows)
    and the incremental-extend slot remap, so the two cannot drift.

    Numerical: [closed-right bins][overflow][NaN].
    Categorical: [known cats][other][negative/NaN]."""
    N = x.shape[0]
    if cats is not None:
        nan = np.isnan(x)
        neg = ~nan & (x < 0)
        cat = np.trunc(np.where(nan | neg, 0, x))
        if cats.size:
            pos = np.clip(np.searchsorted(cats, cat), 0, cats.size - 1)
            known = cats[pos] == cat
        else:
            # empty bitset (all categories go right): every value maps
            # to the "other" slot
            pos = np.zeros(N, np.int64)
            known = np.zeros(N, bool)
        b = np.where(known, pos, cats.size)          # other
        return np.where(nan | neg, cats.size + 1, b)  # neg/NaN slot
    edges = edges if edges is not None else np.zeros(0, np.float64)
    nan = np.isnan(x)
    b = np.searchsorted(edges, np.where(nan, 0.0, x), side="left")
    return np.where(nan, edges.size + 1, b)


def _node_table(tree, s: int, reps: np.ndarray) -> np.ndarray:
    """Evaluate node s's decision (go-left=1) at each representative
    value — vectorized mirror of tree.h:183-201 / Tree._decision."""
    dt = tree.decision_type[s]
    if dt & K_CATEGORICAL_MASK:
        nan = np.isnan(reps)
        ok = ~nan & (reps >= 0)
        cat = np.trunc(np.where(ok, reps, 0)).astype(np.int64)
        ci = tree.threshold_in_bin[s]
        lo, hi = tree.cat_boundaries[ci], tree.cat_boundaries[ci + 1]
        words = np.asarray(tree.cat_threshold[lo:hi], np.uint32)
        wi = cat // 32
        in_r = ok & (wi < (hi - lo))
        bit = np.zeros(reps.size, bool)
        if in_r.any():
            bit[in_r] = ((words[wi[in_r]]
                          >> (cat[in_r] % 32).astype(np.uint32)) & 1) != 0
        return bit.astype(np.int8)
    mt = (dt >> 2) & 3
    def_left = bool(dt & K_DEFAULT_LEFT_MASK)
    nan = np.isnan(reps)
    fz = np.where(nan & (mt != MissingType.NAN), 0.0, reps)
    miss = (((mt == MissingType.ZERO)
             & (fz >= -_ZERO_EPS) & (fz <= _ZERO_EPS))
            | ((mt == MissingType.NAN) & nan))
    with np.errstate(invalid="ignore"):
        go_left = np.where(miss, def_left, fz <= tree.threshold[s])
    return go_left.astype(np.int8)


@jax.jit
def _codes_from_x(x, E, off32, nan_slot):
    """f32 rows -> feature-major global one-hot codes on device."""
    bins = jnp.sum(x[:, :, None] > E[None], axis=2).astype(jnp.int32)
    codes = jnp.where(jnp.isnan(x), nan_slot[None], off32[None] + bins)
    return codes.T


@functools.partial(jax.jit, static_argnames=("offsets", "row_tile",
                                             "interpret"))
def forest_predict_from_x(x, E, off32, nan_slot, W, P, tgt, leaf, cls,
                          *, offsets,
                          row_tile=autotune.DEFAULT_ROW_TILE,
                          interpret=False):
    """Device binning + forest kernel in ONE dispatch."""
    codes_t = _codes_from_x(x, E, off32, nan_slot)
    return forest_predict_pallas(codes_t, W, P, tgt, leaf, cls,
                                 offsets=offsets, row_tile=row_tile,
                                 interpret=interpret)


def _f32_exact(X64: np.ndarray, X32: np.ndarray) -> bool:
    """True when every finite value round-trips f64 -> f32 -> f64."""
    with np.errstate(invalid="ignore"):
        same = (X32.astype(np.float64) == X64) | np.isnan(X64)
    return bool(same.all())


@partial(jax.jit, static_argnums=(9, 10))
def _run_chunk_from_x(x, E, off32, nan_slot, W, P, tgt, leaf, clsOH,
                      Wtot: int, pred_leaf: bool):
    """f32 rows -> codes on device (edges pre-rounded so the f32
    compare reproduces the host's f64 searchsorted exactly), then the
    shared kernel. The codes computation is shared with the Pallas
    path (_codes_from_x) so the binning semantics cannot diverge."""
    codes = _codes_from_x(x, E, off32, nan_slot).T
    return _kernel(codes, W, P, tgt, leaf, clsOH, Wtot, pred_leaf)


@partial(jax.jit, static_argnums=(6, 7))
def _run_chunk(codes, W, P, tgt, leaf, clsOH, Wtot: int,
               pred_leaf: bool):
    """codes [n, F] int32 -> scores [n, K] f32 (or leaf idx [n, T])."""
    return _kernel(codes, W, P, tgt, leaf, clsOH, Wtot, pred_leaf)


def _kernel(codes, W, P, tgt, leaf, clsOH, Wtot: int, pred_leaf: bool):
    n = codes.shape[0]
    from ..utils.device import on_tpu
    # int8 / bf16 feed the MXU's fast paths; the CPU backend's dot
    # lacks those mixed kernels, so it runs f32 (values are exact
    # small ints either way)
    lut_t = jnp.int8 if on_tpu() else jnp.float32
    acc_t = jnp.int32 if on_tpu() else jnp.float32
    mm_t = jnp.bfloat16 if on_tpu() else jnp.float32
    # one-hot row build: one scatter, no [n, F, Wtot] intermediate
    OH = jnp.zeros((n, Wtot), lut_t)
    OH = OH.at[jnp.arange(n)[:, None], codes].set(lut_t(1))

    def step(acc, xs):
        Wc, Pc, tgtc, leafc, clsc = xs
        TC, S, L = Pc.shape
        # node decisions: int8 MXU lookup, C in {0, 1}
        C = jax.lax.dot_general(
            OH, Wc.astype(lut_t), (((1,), (0,)), ((), ())),
            preferred_element_type=acc_t)
        C = C.reshape(n, TC, S).astype(mm_t)
        # signed ancestor-agreement count per leaf (exact ints < 256)
        E = jnp.einsum("nts,tsl->ntl", C, Pc.astype(mm_t),
                       preferred_element_type=jnp.float32)
        match = (E == tgtc[None]).astype(jnp.float32)
        if pred_leaf:
            li = jnp.argmax(match, axis=2).astype(jnp.int32)
            return acc, li
        # HIGHEST: default matmul precision truncates f32 operands to
        # bf16 (on CPU XLA too, shape-dependent) — leaf values and the
        # class scatter must stay exact f32
        val = jnp.einsum("ntl,tl->nt", match, leafc,
                         precision=jax.lax.Precision.HIGHEST)
        acc = acc + jnp.matmul(val, clsc,
                               precision=jax.lax.Precision.HIGHEST)
        return acc, None

    acc0 = jnp.zeros((n, clsOH.shape[-1]), jnp.float32)
    acc, ys = jax.lax.scan(step, acc0, (W, P, tgt, leaf, clsOH))
    if pred_leaf:
        return jnp.moveaxis(ys, 0, 1).reshape(n, -1)
    return acc


# --- fused forest kernel ---------------------------------------------------
#
# The XLA scan above materializes the node-decision matrix C and the
# ancestor-agreement counts E in HBM between its three contractions;
# at 500 trees x 1M rows that traffic alone costs more than the math.
# The Pallas kernel keeps the whole chain in VMEM: build the one-hot
# tile from codes, run both int8 MXU dots, fuse the match compare and
# leaf-value reduction, and emit ONLY the [N, K] score accumulator.
# One dispatch for the entire forest.

def _forest_kernel(codes_ref, W_ref, P_ref, tgt_ref, leaf_ref, cls_ref,
                   acc_ref, *, F, Wtot, offs, TC, Sp, Lp, K, nt):
    i32 = jnp.int32
    step = pl.program_id(1)

    # Grid is (rows, steps) steps-inner: each [nt, K] accumulator block
    # is visited in CONSECUTIVE iterations (a Pallas requirement for
    # read-modify-write output blocks; a steps-outer order interleaves
    # visits and loses partial sums). The W/P re-fetch per row tile is
    # ~4 MB x steps — cheap at a 2048-row tile.
    # One-hot tile [Wtot, nt] int8, rebuilt per iteration:
    # nt*Wtot compares — noise next to the dots.
    blocks = []
    for f in range(F):
        w = offs[f + 1] - offs[f]
        row = codes_ref[f, :].astype(i32) - offs[f]
        iota = jax.lax.broadcasted_iota(i32, (w, 1), 0)
        blocks.append((row[None, :] == iota).astype(jnp.int8))
    oh = jnp.concatenate(blocks, axis=0)                 # [Wtot, nt]

    # dot 1: every node decision for every row, int8 MXU
    C = jax.lax.dot_general(
        oh, W_ref[0], (((0,), (0,)), ((), ())),
        preferred_element_type=i32)                      # [nt, TC*Sp]
    C8 = C.astype(jnp.int8)                              # values {0,1}

    # dot 2 per tree + fused match/value reduction
    vals = []
    for t in range(TC):
        Ct = C8[:, t * Sp:(t + 1) * Sp]
        E = jax.lax.dot_general(
            Ct, P_ref[0, t], (((1,), (0,)), ((), ())),
            preferred_element_type=i32)                  # [nt, Lp]
        match = (E == tgt_ref[0, t][None, :]).astype(jnp.float32)
        vals.append(jnp.sum(match * leaf_ref[0, t][None, :],
                            axis=1, keepdims=True))      # [nt, 1]
    val = jnp.concatenate(vals, axis=1)                  # [nt, TC]
    contrib = jax.lax.dot_general(
        val, cls_ref[0], (((1,), (0,)), ((), ())),
        # f32 MXU default truncates operands to bf16 — keep the class
        # scatter exact (tiny dot, cost is nil)
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32)              # [nt, K]

    @pl.when(step == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
    acc_ref[...] += contrib


@functools.partial(jax.jit, static_argnames=("offsets", "row_tile",
                                             "interpret"))
def forest_predict_pallas(codes_t, W, P, tgt, leaf, cls, *, offsets,
                          row_tile=autotune.DEFAULT_ROW_TILE,
                          interpret=False):
    """codes_t [F, N] int32 -> scores [N, K] f32, one fused dispatch.

    BlockSpecs come from autotune.forest_block_shapes — the same tuples
    _pallas_tc's VMEM estimate prices, so guard and kernel cannot
    drift."""
    F, N = codes_t.shape
    steps, Wtot, TCSp = W.shape
    _, TC, Sp, Lp = P.shape
    K = cls.shape[-1]
    pad = (-N) % row_tile
    if pad:
        # padded rows get code 0 -> garbage scores, sliced off below
        codes_t = jnp.pad(codes_t, ((0, 0), (0, pad)))
    n_pad = N + pad
    kernel = functools.partial(
        _forest_kernel, F=F, Wtot=Wtot, offs=tuple(offsets), TC=TC,
        Sp=Sp, Lp=Lp, K=K, nt=row_tile)
    blk = autotune.forest_block_shapes(F=F, Wtot=Wtot, TC=TC, Sp=Sp,
                                       Lp=Lp, K=K, row_tile=row_tile)
    acc = pl.pallas_call(
        kernel,
        grid=(n_pad // row_tile, steps),
        in_specs=[
            pl.BlockSpec(blk["codes"], lambda r, t: (0, r),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(blk["W"], lambda r, t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(blk["P"], lambda r, t: (t, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(blk["tgt"], lambda r, t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(blk["leaf"], lambda r, t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(blk["cls"], lambda r, t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(blk["acc"], lambda r, t: (r, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_pad, K), jnp.float32),
        compiler_params=autotune.tpu_compiler_params(),
        interpret=interpret,
    )(codes_t, W, P, tgt, leaf, cls)
    return acc[:N]


# --- fused forest kernel, Pallas GPU (Triton) ------------------------------
#
# Same math as _forest_kernel, re-shaped for a CTA grid. Differences
# forced by the Triton lowering:
#
#   * grid is (row blocks,) ONLY — the steps axis moves into an
#     in-kernel fori_loop so the score accumulator lives in registers
#     instead of a revisited output block (Triton has no sequential
#     multi-visit output-block contract to lean on).
#   * the one-hot [Wtot, nt] tile + MXU dot is replaced by F row
#     gathers of the step's W table: C[n, :] = sum_f W[code_f(n), :].
#     Addition of {-1, 0, 1} int8 rows in feature order gives the
#     identical integer C the one-hot contraction produces.
#   * step-indexed stacks are pre-flattened ([steps*Wtot, TC*Sp] etc.)
#     so every in-loop access is either a traced-scalar row or a
#     traced-vector gather — both lower on Triton and interpret alike.
#
# Bit-equality vs forest_predict_pallas(interpret=True) at the same
# row_tile: C and E are exact small integers under any association,
# the match/leaf reduction has at most one nonzero per (row, tree),
# and the only order-sensitive f32 sums — the HIGHEST-precision class
# dot and the step accumulator — run over identical shapes in
# identical step order. tests/test_gpu_tier.py pins this bitwise.

def _gpu_forest_kernel(codes_ref, W_ref, P_ref, tgt_ref, leaf_ref,
                       cls_ref, acc_ref, *, F, Wtot, TC, Sp, Lp, K,
                       steps, nt):
    i32 = jnp.int32
    codes = codes_ref[...].astype(i32)                   # [F, nt]

    def step_body(s, acc):
        base = s * Wtot
        # node decisions via F row gathers (codes carry the global
        # feature offset already, so they index W's node axis directly)
        C = jnp.zeros((nt, TC * Sp), i32)
        for f in range(F):
            C = C + W_ref[base + codes[f, :], :].astype(i32)
        C8 = C.astype(jnp.int8)                          # values {0,1}
        vals = []
        for t in range(TC):
            j = s * TC + t
            Ct = C8[:, t * Sp:(t + 1) * Sp]
            E = jax.lax.dot_general(
                Ct, P_ref[j], (((1,), (0,)), ((), ())),
                preferred_element_type=i32)              # [nt, Lp]
            match = (E == tgt_ref[j][None, :]).astype(jnp.float32)
            vals.append(jnp.sum(match * leaf_ref[j][None, :],
                                axis=1, keepdims=True))  # [nt, 1]
        val = jnp.concatenate(vals, axis=1)              # [nt, TC]
        contrib = jax.lax.dot_general(
            val, cls_ref[s], (((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32)          # [nt, K]
        return acc + contrib

    acc_ref[...] = jax.lax.fori_loop(
        0, steps, step_body, jnp.zeros((nt, K), jnp.float32))


@functools.partial(jax.jit, static_argnames=("offsets", "row_tile",
                                             "interpret"))
def forest_predict_pallas_gpu(codes_t, W, P, tgt, leaf, cls, *,
                              offsets,
                              row_tile=autotune.DEFAULT_GPU_ROW_TILE,
                              interpret=False):
    """codes_t [F, N] int32 -> scores [N, K] f32 on the GPU backend.

    Accepts the SAME device stacks as forest_predict_pallas (one
    _device_arrays_pallas build serves both kernels); the step axis is
    flattened here so the in-kernel loop indexes with plain scalars."""
    del offsets   # codes are globally offset; kept for call symmetry
    F, N = codes_t.shape
    steps, Wtot, TCSp = W.shape
    _, TC, Sp, Lp = P.shape
    K = cls.shape[-1]
    pad = (-N) % row_tile
    if pad:
        # padded rows get code 0 -> garbage scores, sliced off below
        codes_t = jnp.pad(codes_t, ((0, 0), (0, pad)))
    n_pad = N + pad
    kernel = functools.partial(
        _gpu_forest_kernel, F=F, Wtot=Wtot, TC=TC, Sp=Sp, Lp=Lp, K=K,
        steps=steps, nt=row_tile)
    acc = pl.pallas_call(
        kernel,
        grid=(n_pad // row_tile,),
        in_specs=[
            pl.BlockSpec((F, row_tile), lambda r: (0, r)),
            pl.BlockSpec((steps * Wtot, TCSp), lambda r: (0, 0)),
            pl.BlockSpec((steps * TC, Sp, Lp), lambda r: (0, 0, 0)),
            pl.BlockSpec((steps * TC, Lp), lambda r: (0, 0)),
            pl.BlockSpec((steps * TC, Lp), lambda r: (0, 0)),
            pl.BlockSpec((steps, TC, K), lambda r: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((row_tile, K), lambda r: (r, 0)),
        out_shape=jax.ShapeDtypeStruct((n_pad, K), jnp.float32),
        compiler_params=(None if interpret
                         else autotune.gpu_compiler_params()),
        interpret=interpret,
    )(codes_t,
      W.reshape(steps * Wtot, TCSp),
      P.reshape(steps * TC, Sp, Lp),
      tgt.reshape(steps * TC, Lp),
      leaf.reshape(steps * TC, Lp),
      cls)
    return acc[:N]


def forest_predict_from_x_gpu(x, E, off32, nan_slot, W, P, tgt, leaf,
                              cls, *, offsets,
                              row_tile=autotune.DEFAULT_GPU_ROW_TILE,
                              interpret=False):
    """Device binning + GPU forest kernel in ONE dispatch."""
    codes_t = _codes_from_x(x, E, off32, nan_slot)
    return forest_predict_pallas_gpu(codes_t, W, P, tgt, leaf, cls,
                                     offsets=offsets, row_tile=row_tile,
                                     interpret=interpret)
