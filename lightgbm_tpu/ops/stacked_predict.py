"""Whole-model device prediction as one jitted scan of MXU matmuls.

The reference predicts by walking every tree per row under OpenMP
(src/boosting/gbdt_prediction.cpp:9-30, include/LightGBM/tree.h:212-266).
A pointer walk is the wrong shape for a TPU — data-dependent hops defeat
both the MXU and the vector unit. Instead the whole ensemble is lowered
to three dense contractions per tree chunk:

1.  Host-side, every feature's node thresholds become closed-right bin
    edges; raw rows are binned once (exact float64 searchsorted). Every
    node becomes a *decision table* over its feature's bins — built by
    evaluating the node's own host decision function (missing handling,
    default-left, categorical bitsets: tree.h:183-201) at one
    representative value per bin, so the device path agrees with the
    host path by construction.
2.  ``C[n, s] = OH @ W`` — an int8 one-hot matmul looks up every node
    decision for every row at the int8 MXU rate.
3.  A per-tree batched einsum against the signed ancestor matrix
    ``P[t, s, l]`` (+1 = leaf l sits in s's left subtree, -1 = right)
    counts how many ancestor decisions point at each leaf; the row's
    leaf is the one whose count equals its depth. One more einsum with
    the leaf values accumulates per-class scores.

No gathers, no per-tree dispatch: a 500-tree model predicts in one
host->device upload per row chunk and ~T/TC fused scan steps.
"""
from __future__ import annotations

from functools import partial
from typing import List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..io.binning import MissingType
from ..utils import log

# decision_type bit layout (models/tree.py, mirroring tree.h)
K_CATEGORICAL_MASK = 1
K_DEFAULT_LEFT_MASK = 2

_ZERO_EPS = 1e-35
# per-feature table-width cap: categorical features whose bitsets cover
# more distinct categories than this fall back to the host path
MAX_FEATURE_WIDTH = 1024
TREE_CHUNK = 8


class StackedModel:
    """Host-built stacked arrays for a list of trees + the jitted runner."""

    def __init__(self, trees: List, num_features: int, num_class: int):
        self.num_class = num_class
        self.num_trees = len(trees)
        self.ok = True
        try:
            self._build(trees, num_features)
        except _FallbackError as e:
            log.warning("stacked predict unavailable (%s); "
                        "host prediction path will be used", e)
            self.ok = False

    # -- host-side build ----------------------------------------------------

    def _build(self, trees: List, num_features: int) -> None:
        F = num_features
        L = max([t.num_leaves for t in trees] + [2])
        S = L - 1
        T = len(trees)

        # 1. per-feature edges / category sets from every node
        num_thr: List[set] = [set() for _ in range(F)]
        has_zero_mt = np.zeros(F, bool)
        cat_vals: List[set] = [set() for _ in range(F)]
        is_cat_feat = np.zeros(F, bool)
        for t in trees:
            for s in range(t.num_leaves - 1):
                f = t.split_feature[s]
                if f >= F:
                    raise _FallbackError(f"node feature {f} >= {F}")
                dt = t.decision_type[s]
                if dt & K_CATEGORICAL_MASK:
                    is_cat_feat[f] = True
                    ci = t.threshold_in_bin[s]
                    lo, hi = t.cat_boundaries[ci], t.cat_boundaries[ci + 1]
                    for wi in range(lo, hi):
                        w = int(t.cat_threshold[wi]) & 0xFFFFFFFF
                        base = (wi - lo) * 32
                        while w:
                            b = (w & -w).bit_length() - 1
                            cat_vals[f].add(base + b)
                            w &= w - 1
                else:
                    num_thr[f].add(float(t.threshold[s]))
                    if (dt >> 2) & 3 == MissingType.ZERO:
                        has_zero_mt[f] = True
        if np.any(is_cat_feat & (np.array(
                [len(s) for s in num_thr]) > 0)):
            raise _FallbackError("feature used both numerically and "
                                 "categorically")

        # 2. per-feature representative values + binning data.
        # Numerical layout: [m closed-right bins][overflow][NaN].
        # Categorical layout: [known cats][other][negative/NaN].
        self._edges: List[Optional[np.ndarray]] = [None] * F
        self._cats: List[Optional[np.ndarray]] = [None] * F
        reps: List[np.ndarray] = []
        widths = np.zeros(F, np.int64)
        for f in range(F):
            if is_cat_feat[f]:
                cs = np.array(sorted(cat_vals[f]), np.float64)
                if cs.size > MAX_FEATURE_WIDTH:
                    raise _FallbackError(
                        f"categorical feature {f} has {cs.size} "
                        f"distinct categories (> {MAX_FEATURE_WIDTH})")
                self._cats[f] = cs
                other = (cs.max() + 1.0) if cs.size else 1.0
                rep = np.concatenate([cs, [other, -1.0]])
            else:
                thr = sorted(num_thr[f])
                if has_zero_mt[f]:
                    # isolate the reference's zero band |x| <= 1e-35
                    # (tree.h:188) into its own bin so a representative
                    # speaks for every value it covers
                    thr = sorted(set(thr) | {
                        np.nextafter(-_ZERO_EPS, -np.inf), _ZERO_EPS})
                edges = np.asarray(thr, np.float64)
                if edges.size > MAX_FEATURE_WIDTH:
                    raise _FallbackError(
                        f"feature {f} has {edges.size} thresholds")
                self._edges[f] = edges
                over = (np.nextafter(edges[-1], np.inf)
                        if edges.size else 0.0)
                rep = np.concatenate([edges, [over, np.nan]])
            widths[f] = rep.size
            reps.append(rep)
        self._offsets = np.concatenate([[0], np.cumsum(widths)])
        Wtot = int(self._offsets[-1])
        self._Wtot = Wtot

        # 3. decision tables, ancestor matrix, targets, leaf values
        W = np.zeros((Wtot, T, S), np.int8)
        P = np.zeros((T, S, L), np.int8)
        tgt = np.full((T, L), 1e9, np.float32)   # padded leaves: no match
        leaf_val = np.zeros((T, L), np.float32)
        for ti, t in enumerate(trees):
            nl = t.num_leaves
            leaf_val[ti, :nl] = np.asarray(t.leaf_value[:nl], np.float32)
            for s in range(nl - 1):
                f = t.split_feature[s]
                o = self._offsets[f]
                W[o:o + widths[f], ti, s] = _node_table(t, s, reps[f])
            # DFS: signed ancestor matrix + per-leaf left-count target
            if nl == 1:
                tgt[ti, 0] = 0.0
                continue
            stack2 = [(0, [])]           # node, ancestor (node, sign) list
            while stack2:
                node, anc = stack2.pop()
                for child, sign in ((t.left_child[node], 1),
                                    (t.right_child[node], -1)):
                    a2 = anc + [(node, sign)]
                    if child < 0:
                        lf = ~child
                        # E = (#left-ancestors gone left)
                        #   - (#right-ancestors gone left) == nLeft
                        # exactly when every ancestor decision points
                        # at this leaf
                        tgt[ti, lf] = sum(1 for _, sg in a2 if sg > 0)
                        for sn, sg in a2:
                            P[ti, sn, lf] = sg
                    else:
                        stack2.append((child, a2))

        if W.nbytes > (2 << 30):
            raise _FallbackError(f"W matrix {W.nbytes >> 20} MB")
        self._W_host = W
        self._P_host = P
        self._tgt_host = tgt
        self._leaf_host = leaf_val
        self._S, self._L = S, L
        self._dev_cache: dict = {}

    # -- prediction ---------------------------------------------------------

    def _bin_rows(self, X: np.ndarray) -> np.ndarray:
        """[N, F] float64 -> global one-hot column codes [N, Fm] int32
        (model features only; surplus input columns are ignored)."""
        N = X.shape[0]
        Fm = len(self._offsets) - 1
        codes = np.zeros((N, Fm), np.int32)
        nanc = np.full(N, np.nan)
        for f in range(Fm):
            x = X[:, f] if f < X.shape[1] else nanc
            o = self._offsets[f]
            w = self._offsets[f + 1] - o
            if self._cats[f] is not None:
                cs = self._cats[f]
                nan = np.isnan(x)
                neg = ~nan & (x < 0)
                cat = np.trunc(np.where(nan | neg, 0, x))
                pos = np.searchsorted(cs, cat)
                pos = np.clip(pos, 0, cs.size - 1) if cs.size else pos * 0
                known = (cs.size > 0) & (cs[np.minimum(
                    pos, max(cs.size - 1, 0))] == cat)
                b = np.where(known, pos, cs.size)       # other
                b = np.where(nan | neg, cs.size + 1, b)  # neg/NaN slot
            else:
                edges = self._edges[f]
                nan = np.isnan(x)
                b = np.searchsorted(edges, np.where(nan, 0.0, x),
                                    side="left")
                b = np.where(nan, edges.size + 1, b)
            codes[:, f] = o + b
        return codes

    def _device_arrays(self, first: int, ntree: int):
        key = (first, ntree)
        hit = self._dev_cache.get(key)
        if hit is not None:
            return hit
        # bounded: a learning-curve loop (predict at 10, 20, ... trees)
        # would otherwise pin one device copy of W/P per tree range
        while len(self._dev_cache) >= 4:
            self._dev_cache.pop(next(iter(self._dev_cache)))
        TC = min(TREE_CHUNK, max(ntree - first, 1))
        nt = ntree - first
        steps = -(-nt // TC)
        pad = steps * TC - nt
        sl = slice(first, ntree)

        def padT(a, fill=0.0):
            a = a[sl]
            if pad:
                shape = (pad,) + a.shape[1:]
                a = np.concatenate(
                    [a, np.full(shape, fill, a.dtype)], axis=0)
            return a

        W = np.transpose(self._W_host, (1, 0, 2))[sl]       # [nt, Wtot, S]
        if pad:
            W = np.concatenate(
                [W, np.zeros((pad,) + W.shape[1:], np.int8)])
        W = (W.reshape(steps, TC, self._Wtot, self._S)
              .transpose(0, 2, 1, 3)
              .reshape(steps, self._Wtot, TC * self._S))
        P = padT(self._P_host).reshape(steps, TC, self._S, self._L)
        tgt = padT(self._tgt_host, 1e9).reshape(
            steps, TC, self._L)
        leaf = padT(self._leaf_host).reshape(steps, TC, self._L)
        cls = (np.arange(first, first + steps * TC) % self.num_class)
        clsOH = np.eye(self.num_class, dtype=np.float32)[cls].reshape(
            steps, TC, self.num_class)
        if pad:   # padded trees: no leaf ever matches, but zero the class
            clsOH[-1, TC - pad:, :] = 0.0
        out = (jnp.asarray(W), jnp.asarray(P.astype(np.int8)),
               jnp.asarray(tgt), jnp.asarray(leaf), jnp.asarray(clsOH))
        self._dev_cache[key] = out
        return out

    def predict(self, X: np.ndarray, first: int = 0,
                ntree: Optional[int] = None,
                pred_leaf: bool = False,
                row_chunk: int = 65536) -> np.ndarray:
        """Raw scores [K, N] (or leaf indices [N, ntree-first] int32)."""
        ntree = self.num_trees if ntree is None else ntree
        X = np.ascontiguousarray(np.asarray(X, np.float64))
        codes = self._bin_rows(X)
        dev = self._device_arrays(first, ntree)
        N = X.shape[0]
        # pad rows to a power-of-two bucket so repeated odd-sized calls
        # reuse one compiled kernel instead of recompiling per shape
        bucket = min(row_chunk, max(256, 1 << (N - 1).bit_length()))
        pad = (-N) % bucket
        if pad:
            codes = np.concatenate([codes, np.zeros(
                (pad, codes.shape[1]), np.int32)])
        outs = []
        for c0 in range(0, N + pad, bucket):
            chunk = codes[c0:c0 + bucket]
            outs.append(_run_chunk(jnp.asarray(chunk), *dev,
                                   self._Wtot, pred_leaf))
        if pred_leaf:
            out = np.concatenate([np.asarray(o) for o in outs], axis=0)
            return out[:N, :ntree - first]
        return np.concatenate(
            [np.asarray(o) for o in outs],
            axis=0)[:N].T.astype(np.float64)


class _FallbackError(Exception):
    pass


def _node_table(tree, s: int, reps: np.ndarray) -> np.ndarray:
    """Evaluate node s's decision (go-left=1) at each representative
    value — vectorized mirror of tree.h:183-201 / Tree._decision."""
    dt = tree.decision_type[s]
    if dt & K_CATEGORICAL_MASK:
        nan = np.isnan(reps)
        ok = ~nan & (reps >= 0)
        cat = np.trunc(np.where(ok, reps, 0)).astype(np.int64)
        ci = tree.threshold_in_bin[s]
        lo, hi = tree.cat_boundaries[ci], tree.cat_boundaries[ci + 1]
        words = np.asarray(tree.cat_threshold[lo:hi], np.uint32)
        wi = cat // 32
        in_r = ok & (wi < (hi - lo))
        bit = np.zeros(reps.size, bool)
        if in_r.any():
            bit[in_r] = ((words[wi[in_r]]
                          >> (cat[in_r] % 32).astype(np.uint32)) & 1) != 0
        return bit.astype(np.int8)
    mt = (dt >> 2) & 3
    def_left = bool(dt & K_DEFAULT_LEFT_MASK)
    nan = np.isnan(reps)
    fz = np.where(nan & (mt != MissingType.NAN), 0.0, reps)
    miss = (((mt == MissingType.ZERO)
             & (fz >= -_ZERO_EPS) & (fz <= _ZERO_EPS))
            | ((mt == MissingType.NAN) & nan))
    with np.errstate(invalid="ignore"):
        go_left = np.where(miss, def_left, fz <= tree.threshold[s])
    return go_left.astype(np.int8)


@partial(jax.jit, static_argnums=(6, 7))
def _run_chunk(codes, W, P, tgt, leaf, clsOH, Wtot: int,
               pred_leaf: bool):
    """codes [n, F] int32 -> scores [n, K] f32 (or leaf idx [n, T])."""
    n = codes.shape[0]
    from ..utils.device import on_tpu
    # int8 / bf16 feed the MXU's fast paths; the CPU backend's dot
    # lacks those mixed kernels, so it runs f32 (values are exact
    # small ints either way)
    lut_t = jnp.int8 if on_tpu() else jnp.float32
    acc_t = jnp.int32 if on_tpu() else jnp.float32
    mm_t = jnp.bfloat16 if on_tpu() else jnp.float32
    # one-hot row build: one scatter, no [n, F, Wtot] intermediate
    OH = jnp.zeros((n, Wtot), lut_t)
    OH = OH.at[jnp.arange(n)[:, None], codes].set(lut_t(1))

    def step(acc, xs):
        Wc, Pc, tgtc, leafc, clsc = xs
        TC, S, L = Pc.shape
        # node decisions: int8 MXU lookup, C in {0, 1}
        C = jax.lax.dot_general(
            OH, Wc.astype(lut_t), (((1,), (0,)), ((), ())),
            preferred_element_type=acc_t)
        C = C.reshape(n, TC, S).astype(mm_t)
        # signed ancestor-agreement count per leaf (exact ints < 256)
        E = jnp.einsum("nts,tsl->ntl", C, Pc.astype(mm_t),
                       preferred_element_type=jnp.float32)
        match = (E == tgtc[None]).astype(jnp.float32)
        if pred_leaf:
            li = jnp.argmax(match, axis=2).astype(jnp.int32)
            return acc, li
        val = jnp.einsum("ntl,tl->nt", match, leafc)
        return acc + val @ clsc, None

    acc0 = jnp.zeros((n, clsOH.shape[-1]), jnp.float32)
    acc, ys = jax.lax.scan(step, acc0, (W, P, tgt, leaf, clsOH))
    if pred_leaf:
        return jnp.moveaxis(ys, 0, 1).reshape(n, -1)
    return acc
