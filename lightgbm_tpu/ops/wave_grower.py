"""Wave-batched on-device leaf-wise tree grower.

TPU-native counterpart of SerialTreeLearner::Train (reference:
src/treelearner/serial_tree_learner.cpp:157-221), round-2 redesign.

Round 1 compiled the whole leaf-wise loop as ``num_leaves - 1``
shape-static steps, each paying one full-data histogram pass for ONE
leaf — O(N * L) row-histogram work per tree. The reference avoids that
with smaller-child construction + subtraction, but its per-split
histogram still touches the split leaf's rows via gather — a
random-access pattern TPUs do poorly.

The round-2 answer is the **wave**: one ``lax.while_loop`` step splits
the top-``W`` leaves by gain simultaneously, and ONE full-data Pallas
pass (ops/hist_wave.py) produces all ``W`` smaller-child histograms at
the cost of one pass — the idle MXU output lanes of a single-leaf pass
carry the other leaves' channels. Sibling histograms come from
parent - smaller subtraction (feature_histogram.hpp:68) out of a
preallocated HBM pool. Row-histogram work per tree drops to
O(N * L / W), a ~W x win, with no gathers anywhere.

``wave_size=1`` reproduces the reference's exact leaf-wise semantics
(split strictly one best leaf at a time). For larger W the tree can
differ from strict leaf-wise only when the leaf budget runs out
mid-wave; quality is leaf-wise-grade because waves split in gain order.

Leaf numbering matches Tree::Split: each split's left child keeps the
parent's leaf index, the right child takes the next free index; within
a wave, new indices are assigned in gain-rank order.
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .autotune import DEFAULT_HIST_CHUNK
from .grower import TreeRecord
from .hist_wave import (fused_partition_histogram_pallas, wave_histogram)
from .partition import member_column, row_goes_right
from .split import (FeatureMeta, SplitParams, SplitResult, KMIN_SCORE,
                    calculate_leaf_output, find_best_split)


class WaveGrowerConfig(NamedTuple):
    """Static compile-time configuration of one wave grower."""
    num_leaves: int
    num_bins: int          # padded global B
    wave_size: int = 16
    max_depth: int = -1
    chunk: int = 0         # rows per kernel step (0 = impl default)
    hp: SplitParams = SplitParams()
    use_pallas: bool | None = None   # None = auto by backend
    # histogram accumulation: "highest" = bf16 hi/lo exact-product
    # decomposition (f32-grade sums, W <= 25), "default" = single bf16
    # (W <= 42/32). Plumbed from config.tpu_use_dp.
    precision: str = "highest"
    # exact-tier channel layout (precision="highest" only; autotuned
    # per geometry, ops/autotune.py tune_exact_tier): "hilo5" = the
    # original 5-channel hi/lo rows (W <= 24); "hilo4" = 4 channels +
    # a second count dot (W <= 32); "hilo3" = the fused hess/count
    # plane for constant-unit-hessian objectives (W <= 40). All three
    # reconstruct identical f32-grade sums (ops/hist_wave.py); the
    # wave-width cap — passes per tree — is what they trade.
    exact_variant: str = "hilo5"
    # fused partition+histogram kernel (ONE data pass per wave instead
    # of W partition passes + a histogram pass). None = auto: on
    # whenever the Pallas path is on and W fits; interpret mode is used
    # off-TPU so tests exercise the same code path.
    fused: bool | None = None
    # forced splits (forcedsplits_filename, serial_tree_learner.cpp:546
    # ForceSplits): BFS-ordered ((parent_leaf, inner_feature, bin), ...)
    # applied as a fixed prefix before gain-driven growth
    forced: tuple = ()
    # count-proxy (int8 only): drop the count channel from the MXU
    # histogram dot so 2 channels x W <= 128 lanes buys waves up to 64
    # leaves wide (fewer full-data passes per tree). Per-bin counts are
    # synthesized as hessian-proportional estimates (they only gate
    # min_data_in_leaf during candidate evaluation); per-LEAF counts
    # stay EXACT — each wave's kernel counts the rows it moved, so
    # leaf_count/internal_count in the model match the exact path.
    count_proxy: bool = False
    # 4-bit packed HBM bins (count-proxy tier only, max_bin <= 16):
    # grow() receives bins_t as [ceil(F/2), N] bytes with two features'
    # nibbles per byte (reference Dense4bitsBin, dense_nbits_bin.hpp);
    # the fused kernel unpacks in VMEM, halving HBM residency. The
    # non-fused fallback unpacks once up front.
    packed4: bool = False
    # quantized histogram reduction (int8 + data-parallel only,
    # config.tpu_quantized_psum): the hist_reduce_fn collective sees
    # the RAW int32 quantized histogram and dequantization happens
    # AFTER the psum — exact integer addition on the wire (LightGBM's
    # communication-compression analog). Sound because the
    # quantization scales are GLOBAL (max_reduce_fn = pmax), so the
    # scale factors commute with the cross-shard sum.
    quant_psum: bool = False
    # packed psum wire (config.tpu_psum_wire, quant_psum only): dtype
    # the quantized histogram payload crosses the collective in.
    # "int32" is the legacy wire; "int16"/"int8" engage when the
    # 127 * n_rows_global wrap bound proves the narrow sum exact
    # (ops/autotune.py tune_psum_wire — the narrowing/widening casts
    # and the integer psum are then all BIT-identical to int32). The
    # field lives here, not just in the reduce closure, so the
    # step-cache geometry key (models/gbdt.py _step_geometry_key)
    # separates programs compiled for different wires.
    psum_wire: str = "int32"
    # overlap-structured collective (config.tpu_async_psum): number of
    # independent slot psums the wave-histogram collective is split
    # into along the feature axis (parallel/learners.py
    # make_hist_reduce). 1 = one monolithic psum; 2 = double-buffered
    # slots XLA can schedule against local compute. psum is
    # elementwise across shards, so any slot count is bit-identical.
    psum_slots: int = 1
    # sparse histogram tier (config.tpu_sparse, CSR-native datasets):
    # grow() receives ``bins_t`` as a TUPLE (dense [F, N] bins,
    # (codes, feat, row, zero_bins) coordinate planes) and wave
    # histograms accumulate by scatter over the nnz explicit entries
    # plus a default-bin completion (ops/hist_wave.py
    # wave_histogram_sparse) instead of the dense one-hot pass; the
    # dense matrix stays resident for the partition. Serial learner
    # only; excludes the fused kernel, count-proxy, packed4 and
    # injected seams.
    sparse_hist: bool = False
    # resolved histogram route (ops/autotune.py tune_hist_route):
    # "pallas-tpu" | "pallas-gpu" | "fused-xla" | "two-pass"; "" = auto
    # by backend. models/gbdt.py stamps the resolved value here so the
    # step-cache geometry key separates per-backend programs — a
    # checkpoint restored onto a different device kind re-resolves and
    # recompiles instead of replaying the wrong kernel family.
    route: str = ""


class _State(NamedTuple):
    leaf_ids: jax.Array        # [N]
    hist: jax.Array            # [L, F_hist, B, 3] pool
    # per-leaf best-split table (SplitResult fields, [L] each)
    t_gain: jax.Array
    t_feature: jax.Array
    t_bin: jax.Array
    t_default_left: jax.Array
    t_left_output: jax.Array
    t_right_output: jax.Array
    t_left_count: jax.Array
    t_right_count: jax.Array
    t_left_sum_g: jax.Array
    t_left_sum_h: jax.Array
    t_right_sum_g: jax.Array
    t_right_sum_h: jax.Array
    t_is_cat: jax.Array        # [L] bool
    t_cat_words: jax.Array     # [L, 8] int32 left-set bin bitset
    # per-leaf aggregates
    leaf_output: jax.Array
    leaf_count: jax.Array
    leaf_sum_g: jax.Array
    leaf_sum_h: jax.Array
    leaf_depth: jax.Array
    num_leaves: jax.Array      # scalar int32
    n_splits: jax.Array        # scalar int32 (= num_leaves - 1)
    go_on: jax.Array           # scalar bool
    rec: TreeRecord


_SUM_BLOCK = 8192


def _stable_sum(v: jax.Array) -> jax.Array:
    """Shape-stable f32 row reduction: fixed-width blocks reduced
    per-block, then accumulated SEQUENTIALLY. A zero-padded tail (the
    step cache's row bucketing, ops/step_cache.py) then cannot perturb
    rounding — appended blocks are all-+0.0 and add exact zeros to the
    running total, so bucket-padded training reproduces the exact-shape
    run's root aggregates bit-for-bit. A plain ``jnp.sum`` re-shapes
    its reduction tree with the array length, changing last-bit
    rounding when only the padded width changed (observed as 1-ulp
    root internal_value drift)."""
    n = v.shape[0]
    pad = (-n) % _SUM_BLOCK
    if pad:
        v = jnp.concatenate([v, jnp.zeros(pad, v.dtype)])
    bs = jnp.sum(v.reshape(-1, _SUM_BLOCK), axis=1)
    if bs.shape[0] == 1:
        return bs[0]
    return jax.lax.fori_loop(
        1, bs.shape[0], lambda i, acc: acc + bs[i], bs[0])


def _mix32(x: jax.Array) -> jax.Array:
    """lowbias32 integer finalizer (uint32 -> well-mixed uint32) — the
    stochastic-rounding hash. Wrapping uint32 arithmetic everywhere."""
    x = x ^ (x >> jnp.uint32(16))
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> jnp.uint32(15))
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> jnp.uint32(16))
    return x


def _hash_uniform(idx: jax.Array, salt: jax.Array) -> jax.Array:
    """Per-row uniform draws in [0, 1) keyed by GLOBAL row index +
    salt. Position-deterministic: the draw of row i is the same no
    matter how rows are sharded across devices, so quantized training
    gives identical trees on 1 chip and on a row-sharded mesh (a
    positional PRNG stream like jax.random.uniform(key, (n,)) would
    not — its counter layout depends on the local shard length)."""
    return (_mix32(idx ^ salt) >> jnp.uint32(8)).astype(
        jnp.float32) * jnp.float32(2.0 ** -24)


def _store_batch(table, idx, vals, active):
    """Masked scatter of per-slot values into a table.

    Inactive slots are sent to index ``len(table)`` — out of bounds HIGH,
    which ``mode="drop"`` discards. (A -1 sentinel would NOT be dropped:
    jax wraps negative scatter indices python-style, silently writing the
    last element.)
    """
    idx = jnp.where(active, idx, table.shape[0])
    return table.at[idx].set(vals, mode="drop")


def make_wave_grower(cfg: WaveGrowerConfig, meta: FeatureMeta,
                     hist_fn=None, split_fn=None, partition_fn=None,
                     reduce_fn=None, hist_reduce_fn=None,
                     max_reduce_fn=None, row_offset_fn=None, jit=True):
    """Build ``grow(bins_t, grad, hess, sample_mask, feature_mask,
    meta=None)``.

    bins_t is FEATURE-MAJOR [F, N] (see ops/hist_wave.py).

    ``meta``: optional TRACED FeatureMeta overriding the factory-time
    constant — the compiled-step registry (ops/step_cache.py) passes
    the per-booster bin metadata as an argument so two boosters binned
    on different data share one compiled program. Omitted (the legacy
    call shape), the factory meta embeds as trace constants exactly as
    before. The default split/partition seams thread it; INJECTED
    seams keep their own closure meta (the learners that inject them
    are not cacheable).

    Injection seams for the parallel learners (SURVEY §2.2):
      hist_fn(bins_t, g, h, leaf_ids, wave_leaves) -> [W, F_hist, B, 3]
        (feature-parallel: local feature slice; voting: local hist,
        election in split_fn)
      split_fn(hists [M,F,B,3], sg [M], sh [M], nd [M], fmask, can [M])
        -> SplitResult of [M] arrays with GLOBAL feature indices
      partition_fn(bins_t, leaf_ids, wl, new_ids, feat, tbin, dleft,
                   active) -> new leaf_ids  (local rows)
      reduce_fn(x) -> global sum of a locally-summed scalar
      hist_reduce_fn(hist) -> cross-device sum of a wave histogram
        (data-parallel psum). Unlike hist_fn, this seam COMPOSES with
        the fused partition+histogram kernel: each shard partitions and
        histograms its own rows in one Pallas pass and only the [W, F,
        B, 3] result rides the collective — the multi-chip path keeps
        the single-chip kernel. With ``cfg.quant_psum`` the seam sees
        the RAW int32 quantized histogram (dequantization runs after
        the collective).
      row_offset_fn(n_local) -> this shard's first GLOBAL row index
        (data/voting: axis_index * n_local; default 0). Feeds the
        stochastic-rounding hash so the quantization draw of a row is
        identical no matter how rows are sharded.

    All default to serial single-device implementations. ``jit=False``
    returns the raw traceable fn for wrapping in shard_map.
    """
    L = cfg.num_leaves
    W = min(cfg.wave_size, max(L - 1, 1))
    B = cfg.num_bins
    hp = cfg.hp
    meta_const = FeatureMeta(*[jnp.asarray(x) for x in meta])

    # fused partition+histogram path (serial mode only: the parallel
    # learners inject their own hist/partition seams)
    default_seams = (hist_fn is None and partition_fn is None)
    quant = cfg.precision == "int8"
    proxy = bool(cfg.count_proxy)
    if proxy and not quant:
        raise ValueError("count_proxy requires precision='int8' "
                         "(tpu_quantized_hist)")
    if proxy and cfg.forced:
        raise ValueError("count_proxy does not compose with forced "
                         "splits; disable tpu_count_proxy")
    if proxy and (hist_fn is not None or partition_fn is not None):
        raise ValueError("count_proxy does not compose with injected "
                         "histogram/partition seams")
    if cfg.packed4 and not (proxy or cfg.precision == "highest"):
        raise ValueError("packed4 bins require the count-proxy or "
                         "hi/lo exact tier")
    if cfg.packed4 and cfg.forced:
        raise ValueError("packed4 does not compose with forced splits "
                         "(the forced prefix reads unpacked bins); "
                         "disable tpu_packed_bins")
    if cfg.sparse_hist and (proxy or cfg.packed4 or cfg.quant_psum):
        raise ValueError("sparse_hist does not compose with "
                         "count_proxy/packed4/quant_psum")
    if cfg.sparse_hist and (hist_fn is not None
                            or partition_fn is not None):
        raise ValueError("sparse_hist does not compose with injected "
                         "histogram/partition seams")
    if quant and hist_fn is not None:
        # an injected histogram seam must understand quantized g/h —
        # silently dropping gh_scale would produce garbage histograms
        import inspect
        if "gh_scale" not in inspect.signature(hist_fn).parameters:
            raise ValueError(
                "int8 quantized histograms need a hist_fn that "
                "accepts gh_scale (see the EFB bundle seam, "
                "models/gbdt.py)")
    defer = bool(cfg.quant_psum)
    if defer and not quant:
        raise ValueError("quant_psum requires precision='int8' "
                         "(tpu_quantized_hist)")
    if defer and (hist_fn is not None or partition_fn is not None):
        # an injected seam returns DEQUANTIZED f32 histograms; psumming
        # those as if they were the int32 wire would double-scale
        raise ValueError("quant_psum does not compose with injected "
                         "histogram/partition seams")
    # the packed-wire/slot fields are CONSUMED by the data-parallel
    # reduce closure (parallel/learners.py make_hist_reduce); they are
    # validated here because this factory owns the config contract and
    # the step-cache geometry key carries them
    if cfg.psum_wire not in ("int8", "int16", "int32"):
        raise ValueError(f"unknown psum_wire {cfg.psum_wire!r} "
                         f"(want one of int8/int16/int32)")
    if cfg.psum_wire != "int32" and not defer:
        raise ValueError("a psum_wire narrower than int32 rides the "
                         "quantized collective (quant_psum=True); the "
                         "f32 wire cannot be narrowed exactly")
    if cfg.psum_slots < 1:
        raise ValueError(f"psum_slots={cfg.psum_slots} must be >= 1")
    if cfg.exact_variant not in ("hilo5", "hilo4", "hilo3"):
        raise ValueError(f"unknown exact_variant {cfg.exact_variant!r}")
    if cfg.exact_variant != "hilo5":
        if cfg.precision != "highest":
            raise ValueError("exact_variant applies to the exact tier "
                             "(precision='highest') only")
        if hist_fn is not None or partition_fn is not None \
                or cfg.sparse_hist:
            # injected seams build their own histogram layout; the
            # sparse tier scatters (layout-free) but the grower's wave
            # cap must then stay at the injected seam's contract
            raise ValueError("exact_variant does not compose with "
                             "injected histogram/partition seams or "
                             "the sparse tier")
    bundled = jnp.ndim(meta_const.bundle) != 0
    # resolve the histogram route once: an explicit cfg.route pins the
    # kernel family (and rode the step-cache geometry key to get here);
    # otherwise the device kind decides (autotune.tune_hist_route)
    from . import autotune
    if cfg.route and cfg.route not in autotune.HIST_ROUTES:
        raise ValueError(f"unknown hist route {cfg.route!r} "
                         f"(want one of {autotune.HIST_ROUTES})")
    route = cfg.route or autotune.tune_hist_route(
        use_pallas=cfg.use_pallas,
        fused_eligible=cfg.fused is not False)
    gpu_hist = route == "pallas-gpu"
    pallas_hist = route in ("pallas-tpu", "pallas-gpu")
    use_fused = cfg.fused
    if use_fused is None:
        from .hist_wave import (FUSED_MAX_WAVE, FUSED_MAX_WAVE_HILO,
                                FUSED_MAX_WAVE_HILO3,
                                FUSED_MAX_WAVE_HILO4,
                                FUSED_MAX_WAVE_INT8,
                                FUSED_MAX_WAVE_INT8_NC)
        fused_cap = (FUSED_MAX_WAVE_INT8_NC if quant and proxy
                     else FUSED_MAX_WAVE_INT8 if quant
                     else {"hilo5": FUSED_MAX_WAVE_HILO,
                           "hilo4": FUSED_MAX_WAVE_HILO4,
                           "hilo3": FUSED_MAX_WAVE_HILO3}[
                               cfg.exact_variant]
                     if cfg.precision == "highest" else FUSED_MAX_WAVE)
        # the GPU fused kernel accumulates by atomics into global
        # memory — no lane budget, so no wave-width cap applies there
        use_fused = (default_seams and (gpu_hist or W <= fused_cap)
                     and not bundled and not cfg.sparse_hist
                     and pallas_hist)
    if use_fused:
        from ..utils.device import backend_kind, on_tpu
        # interpret mode runs the kernel off its native accelerator
        # (the tier-1 parity suite drives both kernel families on CPU)
        fused_interpret = (backend_kind() != "gpu" if gpu_hist
                           else not on_tpu())
        from .hist_wave import fused_partition_histogram_pallas_gpu
        fused_kernel_fn = (fused_partition_histogram_pallas_gpu
                           if gpu_hist
                           else fused_partition_histogram_pallas)
        fused_chunk = cfg.chunk or (autotune.DEFAULT_GPU_HIST_CHUNK
                                    if gpu_hist else DEFAULT_HIST_CHUNK)
    # off-TPU twin of the fused kernel (ops/hist_wave.py
    # fused_partition_histogram_xla): partition + smaller-child
    # histogram in one traced region, reusing the leaf-membership
    # compares between the two and riding ONE combined scatter —
    # bit-identical to [partition_fn -> hist_fn], so it is the default
    # off-TPU route wherever the Pallas fused kernel would be the
    # on-TPU one. cfg.fused=False opts out (the legacy two-pass
    # pipeline, kept as the parity oracle).
    use_fused_xla = (not use_fused and cfg.fused is not False
                     and default_seams and not bundled
                     and not cfg.sparse_hist
                     and not pallas_hist)
    if use_fused_xla:
        from .hist_wave import fused_partition_histogram_xla

    if hist_fn is None and cfg.sparse_hist:
        # sparse tier: the histogram source is the (dense bins, sparse
        # planes) tuple grow() unpacks — scatter over nnz instead of
        # the dense pass (ops/hist_wave.py)
        from .hist_wave import wave_histogram_sparse

        def hist_fn(src, g, h, leaf_ids, wave_leaves, gh_scale=None):
            bt, sp = src
            return wave_histogram_sparse(
                sp, g, h, leaf_ids, wave_leaves, num_bins=B,
                num_features=bt.shape[0], gh_scale=gh_scale)
    elif hist_fn is None:
        # the two-pass wave histogram rides the resolved route too —
        # "two-pass" maps to the layout-free XLA scatter inside the
        # dispatcher, the pallas tiers to their device kernel
        hist_route = ("two-pass" if route == "fused-xla" else route)

        def hist_fn(bins_t, g, h, leaf_ids, wave_leaves, gh_scale=None):
            return wave_histogram(bins_t, g, h, leaf_ids, wave_leaves,
                                  num_bins=B, chunk=cfg.chunk,
                                  use_pallas=cfg.use_pallas,
                                  precision=cfg.precision,
                                  gh_scale=gh_scale,
                                  dequant=not defer,
                                  variant=cfg.exact_variant,
                                  route=hist_route)

    # default split/partition seams take meta as a CALL parameter (the
    # compiled-step registry passes a traced override); injected seams
    # keep their original signature and closure meta — the learners
    # that inject them never cache-share across boosters
    user_split_fn, user_partition_fn = split_fn, partition_fn

    def split_fn(hists, sg, sh, nd, fmask, can, meta):
        if user_split_fn is not None:
            return user_split_fn(hists, sg, sh, nd, fmask, can)
        return jax.vmap(
            lambda hh, a, b, c, d: find_best_split(
                hh, a, b, c, fmask, meta, hp, d)
        )(hists, sg, sh, nd, can)

    def partition_fn(bins_t, leaf_ids, wl, new_ids, feat, tbin,
                     dleft, active, meta, iscat=None, catw=None):
        if user_partition_fn is not None:
            return user_partition_fn(bins_t, leaf_ids, wl, new_ids,
                                     feat, tbin, dleft, active, iscat,
                                     catw)
        return apply_wave_splits(bins_t, leaf_ids, wl, new_ids, feat,
                                 tbin, dleft, active, meta,
                                 iscat, catw)

    if reduce_fn is None:
        def reduce_fn(x):
            return x

    if hist_reduce_fn is None:
        def hist_reduce_fn(h):
            return h

    if max_reduce_fn is None:
        def max_reduce_fn(x):
            return x

    if row_offset_fn is None:
        def row_offset_fn(n_local):
            return jnp.int32(0)

    def depth_ok(depth):
        if cfg.max_depth > 0:
            return depth < cfg.max_depth
        return jnp.ones_like(depth, dtype=bool)

    def bound_counts(h2, gh_scale):
        """count-proxy: fill the count channel with per-bin LOWER
        BOUNDS derived from the quantized g/h sums themselves —
        |g_q| <= 127 and h_q <= 127 per row, so
        count_bin >= max(|sum g_q|, sum h_q) / 127. Bounds are LOCAL
        per bin (valid under prefix/suffix summation and histogram
        subtraction is never applied to them — callers recompute the
        channel from each child's own g/h). With hp.count_lb the
        min_data_in_leaf gate consumes these conservatively: it can
        over-prune but never admits a split the exact gate would
        reject. Per-LEAF totals stay exact via partition-mask counts."""
        h2 = h2[..., :2]
        sg, sh = gh_scale
        lb = jnp.maximum(jnp.abs(h2[..., 0]) / jnp.float32(sg),
                         h2[..., 1] / jnp.float32(sh)) / 127.0
        return jnp.concatenate([h2, lb[..., None]], axis=-1)

    def grow(bins_t, grad, hess, sample_mask, feature_mask, meta=None):
        """Grow one tree.

        bins_t: [F, N] int bins (feature-major); grad/hess: [N] f32;
        sample_mask: [N] f32 0/1 bagging membership;
        feature_mask: [F] bool usable features this tree;
        meta: optional traced FeatureMeta override (step_cache path) —
        None keeps the factory-time constants.
        Returns (TreeRecord, leaf_ids[N]) — leaf_ids of ALL rows
        (out-of-bag included) for score updates.
        """
        meta = meta_const if meta is None else meta
        _sparse_planes = None
        if cfg.sparse_hist:
            # (dense bins, sparse coordinate planes): the dense matrix
            # serves the partition, the planes the histogram scatters;
            # hist call sites pass the pair through ``hsrc``
            bins_t, _sparse_planes = bins_t
        F, n = bins_t.shape
        f32 = jnp.float32
        if cfg.packed4:
            F = int(feature_mask.shape[0])       # logical features
            if not use_fused:
                # oracle/fallback path: unpack nibbles once up front
                # (row 2p = low nibble of byte row p)
                lo = jnp.bitwise_and(bins_t, jnp.uint8(15))
                hi = jnp.right_shift(bins_t, jnp.uint8(4))
                bins_t = jnp.stack([lo, hi], axis=1).reshape(
                    -1, bins_t.shape[1])[:F]
        # histogram source — bound AFTER the packed4 unpack above may
        # have reassigned bins_t
        hsrc = ((bins_t, _sparse_planes) if cfg.sparse_hist
                else bins_t)
        grad = grad.astype(f32) * sample_mask
        hess = hess.astype(f32) * sample_mask
        in_bag = sample_mask > 0

        if quant:
            # gradient quantization (tpu_quantized_hist): integer-valued
            # g/h in [-127, 127] make every MXU histogram product an
            # exact int8 op at 2x the bf16 rate.
            # GLOBAL quantization scales (max_reduce_fn = pmax in data
            # mode): shard-local scales would make the dequantized psum
            # sums correct but leave count-proxy bounds computed on the
            # GLOBAL histogram invalid (divided by a local scale) and
            # shard-divergent — every shard must see one (sg, sh).
            # max is order-independent, so the pmax of shard maxima
            # equals the single-chip max EXACTLY.
            sg_s = jnp.maximum(max_reduce_fn(jnp.max(jnp.abs(grad))),
                               1e-30) / 127.0
            sh_s = jnp.maximum(max_reduce_fn(jnp.max(hess)),
                               1e-30) / 127.0
            # stochastic rounding keyed by GLOBAL row index (shard
            # offset + local position) and a per-tree salt: unbiased
            # per-bin sums and — unlike a positional PRNG stream —
            # the same draw for the same row under ANY row sharding,
            # so quantized data-parallel training reproduces the
            # single-chip quantized trees. The salt mixes the scale
            # bits with a WRAPPING int32 sum of the raw gradient bits:
            # mod-2^32 adds commute, so the psum of shard-local bit
            # sums equals the single-chip sum exactly (layout
            # invariance), and the stream re-rolls whenever ANY row's
            # gradient moves — scale bits alone would freeze it for
            # constant-bound objectives (L1-family: max|g| and max h
            # never change between trees).
            bg = jax.lax.bitcast_convert_type(
                sg_s.astype(f32), jnp.uint32)
            bh = jax.lax.bitcast_convert_type(
                sh_s.astype(f32), jnp.uint32)
            gbits_sum = reduce_fn(jnp.sum(
                jax.lax.bitcast_convert_type(grad, jnp.int32),
                dtype=jnp.int32))
            salt = (bg ^ ((bh << jnp.uint32(16)) | (bh >> jnp.uint32(16)))
                    ^ _mix32(gbits_sum.astype(jnp.uint32)))
            gidx = (row_offset_fn(n)
                    + jnp.arange(n, dtype=jnp.int32)).astype(jnp.uint32)
            u_g = _hash_uniform(gidx, salt)
            u_h = _hash_uniform(gidx, salt ^ jnp.uint32(0x9E3779B9))
            gq = jnp.clip(jnp.floor(grad / sg_s + u_g), -127.0, 127.0)
            hq = jnp.clip(jnp.floor(hess / sh_s + u_h), 0.0, 127.0)
            gh_scale = (sg_s, sh_s)
            hg, hh = gq, hq            # what histogram passes consume

            def call_hist(bt, lids, wl):
                return hist_fn(bt, hg, hh, lids, wl, gh_scale)
        else:
            gh_scale = None
            hg, hh = grad, hess

            def call_hist(bt, lids, wl):
                return hist_fn(bt, hg, hh, lids, wl)

        def dq(hsum):
            """Dequantize a reduced quantized-wire histogram — identity
            unless cfg.quant_psum deferred the scaling past the
            collective. Handles both the 2-channel proxy wire and the
            3-channel wire (the XLA oracle keeps 3 channels)."""
            if not defer:
                return hsum
            hsum = hsum.astype(f32)
            sgf = jnp.float32(gh_scale[0])
            shf = jnp.float32(gh_scale[1])
            if hsum.shape[-1] == 2:
                return hsum * jnp.stack([sgf, shf])
            return hsum * jnp.stack([sgf, shf, jnp.float32(1.0)])

        # Bagging: leaf_ids tracks ALL rows (out-of-bag rows partition
        # too — scores need their leaf), but histogram passes see
        # out-of-bag rows as leaf -1 so no wave slot counts them.
        def bag_mask_ids(leaf_ids):
            return jnp.where(in_bag, leaf_ids, -1)

        # root: wave histogram with one active slot = leaf 0
        root_wl = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), jnp.full(W - 1, -1, jnp.int32)])
        leaf0 = jnp.zeros(n, jnp.int32)
        if use_fused and (proxy or cfg.packed4):
            # proxy/packed4 root: the partition-free wave kernel in the
            # matching tier — no partition logic to pay for on an
            # unsplit tree, and (packed4) the default hist_fn never
            # sees the packed byte rows the fused path keeps in HBM
            from .hist_wave import (wave_histogram_pallas,
                                    wave_histogram_pallas_gpu)
            wave_kernel = (wave_histogram_pallas_gpu if gpu_hist
                           else wave_histogram_pallas)
            root_chunk = cfg.chunk or (
                autotune.DEFAULT_GPU_HIST_CHUNK if gpu_hist
                else DEFAULT_HIST_CHUNK)
            local_root = wave_kernel(
                bins_t, hg, hh, bag_mask_ids(leaf0), root_wl,
                num_bins=B, chunk=root_chunk,
                interpret=fused_interpret, precision=cfg.precision,
                gh_scale=gh_scale, count_proxy=proxy,
                packed4=cfg.packed4,
                num_features=F if cfg.packed4 else None,
                dequant=not defer, variant=cfg.exact_variant)
        else:
            local_root = call_hist(hsrc, bag_mask_ids(leaf0),
                                   root_wl)              # [W, F, B, 3]
        root_hist = dq(hist_reduce_fn(local_root))
        F_h = root_hist.shape[1]
        if quant:
            # root aggregates as dequantized sums of the SAME integer
            # g/h the histogram passes consume, so later subtractions
            # stay internally consistent — computed directly from
            # hg/hq rather than a histogram column: a hist_fn that
            # zero-pads unowned features (the EFB x feature-parallel
            # seam expands only the local bundle slice) would make a
            # column-derived sum device-dependent. Local sum then the
            # scalar reducer: one collective in every mode. The LOCAL
            # sum accumulates in int32 when the shard's row count
            # provably cannot wrap it (|v| <= 127 so the total is
            # bounded by 127*n < 2^31 — the same bound the Pallas
            # kernels' overflow guard enforces; the XLA fallback path
            # has no such guard, so bigger shards keep the old f32
            # sum, which rounds but never wraps). The exact per-shard
            # total converts to f32 BEFORE the reducer: an int32 psum
            # across D shards could wrap even when every shard is
            # within bound, while the f32 psum of D already-exact
            # totals rounds only D-1 additions.
            if 127 * n < 2 ** 31:
                def acc(v):
                    return jnp.sum(v.astype(jnp.int32)).astype(f32)
            else:
                acc = _stable_sum
            root_g = reduce_fn(acc(hg)) * gh_scale[0]
            root_h = reduce_fn(acc(hh)) * gh_scale[1]
        else:
            # shape-stable sums: bucket-padded and exact-shape boosters
            # must agree bit-for-bit (ops/step_cache.py row bucketing)
            root_g = reduce_fn(_stable_sum(grad))
            root_h = reduce_fn(_stable_sum(hess))
        root_c = reduce_fn(jnp.sum(sample_mask))
        if proxy:
            root_hist = bound_counts(root_hist, gh_scale)
        root_split = split_fn(
            root_hist[:1], root_g[None], root_h[None], root_c[None],
            feature_mask, depth_ok(jnp.zeros(1, jnp.int32)), meta)

        def set0(arr, v):
            return arr.at[0].set(v[0] if v.ndim else v)

        state = _State(
            leaf_ids=leaf0,
            hist=jnp.zeros((L, F_h, B, 3), f32).at[0].set(root_hist[0]),
            t_gain=set0(jnp.full(L, KMIN_SCORE, f32), root_split.gain),
            t_feature=set0(jnp.zeros(L, jnp.int32), root_split.feature),
            t_bin=set0(jnp.zeros(L, jnp.int32), root_split.threshold_bin),
            t_default_left=set0(jnp.zeros(L, bool),
                                root_split.default_left),
            t_left_output=set0(jnp.zeros(L, f32), root_split.left_output),
            t_right_output=set0(jnp.zeros(L, f32),
                                root_split.right_output),
            t_left_count=set0(jnp.zeros(L, f32), root_split.left_count),
            t_right_count=set0(jnp.zeros(L, f32), root_split.right_count),
            t_left_sum_g=set0(jnp.zeros(L, f32), root_split.left_sum_g),
            t_left_sum_h=set0(jnp.zeros(L, f32), root_split.left_sum_h),
            t_right_sum_g=set0(jnp.zeros(L, f32), root_split.right_sum_g),
            t_right_sum_h=set0(jnp.zeros(L, f32), root_split.right_sum_h),
            t_is_cat=set0(jnp.zeros(L, bool), root_split.is_cat),
            t_cat_words=jnp.zeros((L, 8), jnp.int32).at[0].set(
                root_split.cat_words[0] if root_split.cat_words.ndim > 1
                else root_split.cat_words),
            leaf_output=jnp.zeros(L, f32),
            leaf_count=jnp.zeros(L, f32).at[0].set(root_c),
            leaf_sum_g=jnp.zeros(L, f32).at[0].set(root_g),
            leaf_sum_h=jnp.zeros(L, f32).at[0].set(root_h),
            leaf_depth=jnp.zeros(L, jnp.int32),
            num_leaves=jnp.int32(1),
            n_splits=jnp.int32(0),
            go_on=jnp.bool_(True),
            rec=TreeRecord(
                num_leaves=jnp.int32(1),
                split_leaf=jnp.full(L - 1, -1, jnp.int32),
                split_feature=jnp.full(L - 1, -1, jnp.int32),
                split_bin=jnp.zeros(L - 1, jnp.int32),
                split_gain=jnp.zeros(L - 1, f32),
                split_default_left=jnp.zeros(L - 1, bool),
                leaf_output=jnp.zeros(L, f32),
                leaf_count=jnp.zeros(L, f32),
                leaf_sum_g=jnp.zeros(L, f32),
                leaf_sum_h=jnp.zeros(L, f32),
                internal_value=jnp.zeros(L - 1, f32),
                internal_count=jnp.zeros(L - 1, f32),
                split_is_cat=jnp.zeros(L - 1, bool),
                split_cat_words=jnp.zeros((L - 1, 8), jnp.int32),
            ),
        )

        def body(state: _State) -> _State:
            f32 = jnp.float32
            # 1. elect the wave: top-W leaves by gain, capped by budget
            top_gain, wl = jax.lax.top_k(state.t_gain, W)   # [W]
            wl = wl.astype(jnp.int32)
            budget = (L - state.num_leaves).astype(jnp.int32)
            rank = jnp.arange(W, dtype=jnp.int32)
            active = (top_gain > 0.0) & (rank < budget)
            n_act = jnp.sum(active.astype(jnp.int32))
            prefix = jnp.cumsum(active.astype(jnp.int32)) - active
            new_ids = jnp.where(active, state.num_leaves + prefix, -1)
            wl = jnp.where(active, wl, -1)
            # scatter-safe slot indices: OOB-high sentinel so that
            # mode="drop" really drops inactive slots (negative indices
            # would wrap python-style and corrupt the last entries)
            wl_s = jnp.where(active, wl, L)
            new_s = jnp.where(active, new_ids, L)

            # 2. per-slot split params from the table (drop-safe gathers)
            feat = state.t_feature[wl]
            tbin = state.t_bin[wl]
            dleft = state.t_default_left[wl]
            iscat = state.t_is_cat[wl]
            catw = state.t_cat_words[wl]               # [W, 8]
            lcnt = state.t_left_count[wl]
            rcnt = state.t_right_count[wl]
            lg, lh = state.t_left_sum_g[wl], state.t_left_sum_h[wl]
            rg, rh = state.t_right_sum_g[wl], state.t_right_sum_h[wl]
            lo, ro = state.t_left_output[wl], state.t_right_output[wl]

            # 3+4. partition, then smaller-child histograms; siblings by
            # subtraction from the pooled parent histogram. The fused
            # Pallas path does both in ONE data pass (ocl/histogram256's
            # partition-then-accumulate per workgroup, without the W
            # separate partition passes).
            left_smaller = lcnt <= rcnt
            small_ids = jnp.where(left_smaller, wl, new_ids)
            small_ids = jnp.where(active, small_ids, -1)
            if use_fused:
                safe_feat = jnp.maximum(feat, 0)
                tbl = jnp.concatenate([jnp.stack([
                    wl, new_ids, safe_feat, tbin,
                    dleft.astype(jnp.int32),
                    meta.missing_type[safe_feat],
                    meta.default_bin[safe_feat],
                    meta.num_bin[safe_feat], small_ids,
                    iscat.astype(jnp.int32)]), catw.T])      # [18, W]
                fused_out = fused_kernel_fn(
                    bins_t, hg, hh, sample_mask,
                    state.leaf_ids, tbl, num_bins=B,
                    chunk=fused_chunk,
                    interpret=fused_interpret,
                    precision=cfg.precision, gh_scale=gh_scale,
                    any_cat=bool(hp.has_cat), count_proxy=proxy,
                    packed4=cfg.packed4,
                    num_features=F if cfg.packed4 else None,
                    dequant=not defer, variant=cfg.exact_variant)
                leaf_ids, hist_small = fused_out[0], fused_out[1]
                hist_small = dq(hist_reduce_fn(hist_small))
                if proxy:
                    cnt_r = reduce_fn(fused_out[2])
                # out-of-bag rows partition too; their g/h are pre-masked
                # and the count channel rides on sample_mask
            elif use_fused_xla:
                # off-TPU fused route: one traced partition+histogram
                # region reusing the membership compares and the
                # combined 3-channel scatter — bit-identical to the
                # legacy [partition_fn -> call_hist] pipeline below
                safe_feat = jnp.maximum(feat, 0)
                fx = fused_partition_histogram_xla(
                    bins_t, hg, hh, sample_mask, state.leaf_ids,
                    wl, new_ids, feat, tbin, dleft, iscat, catw,
                    small_ids,
                    meta.missing_type[safe_feat],
                    meta.default_bin[safe_feat],
                    meta.num_bin[safe_feat],
                    num_bins=B, count_proxy=proxy,
                    gh_scale=gh_scale if quant else None,
                    dequant=not defer)
                leaf_ids = fx[0]
                hist_small = dq(hist_reduce_fn(fx[1]))
                if proxy:
                    cnt_r = reduce_fn(fx[2])
            else:
                leaf_ids = partition_fn(bins_t, state.leaf_ids, wl,
                                        new_ids, feat, tbin, dleft,
                                        active, meta, iscat, catw)
                hist_small = dq(hist_reduce_fn(
                    call_hist(hsrc, bag_mask_ids(leaf_ids),
                              small_ids)))
                if proxy:
                    # exact in-bag right-child counts (XLA fallback for
                    # the Pallas kernel's partition-mask counting)
                    cnt_r = reduce_fn(jnp.sum(
                        ((leaf_ids[None, :] == new_ids[:, None])
                         & in_bag[None, :]).astype(jnp.float32),
                        axis=1))
            if proxy:
                parent_cnt = state.leaf_count[wl]
                lcnt_x = parent_cnt - cnt_r          # exact (partition)
                rcnt_x = cnt_r
                hist_small = bound_counts(hist_small, gh_scale)
            else:
                lcnt_x, rcnt_x = lcnt, rcnt
            parent_hist = state.hist[wl]                 # [W, F, B, 3]
            hist_large = parent_hist - hist_small
            if proxy:
                # the count channel holds lower bounds, which do NOT
                # survive subtraction — recompute from the large
                # child's own (exact) g/h sums
                hist_large = bound_counts(hist_large, gh_scale)
            ls4 = left_smaller[:, None, None, None]
            hist_left = jnp.where(ls4, hist_small, hist_large)
            hist_right = jnp.where(ls4, hist_large, hist_small)
            pool = state.hist
            pool = pool.at[wl_s].set(hist_left, mode="drop")
            pool = pool.at[new_s].set(hist_right, mode="drop")

            # 5. record the wave's splits at positions n_splits + prefix
            pos = jnp.where(active, state.n_splits + prefix, L - 1)
            parent_out = calculate_leaf_output(
                state.leaf_sum_g[wl], state.leaf_sum_h[wl],
                hp.lambda_l1, hp.lambda_l2, hp.max_delta_step)
            rec = state.rec
            rec = rec._replace(
                num_leaves=rec.num_leaves + n_act,
                split_leaf=rec.split_leaf.at[pos].set(wl, mode="drop"),
                split_feature=rec.split_feature.at[pos].set(
                    feat, mode="drop"),
                split_bin=rec.split_bin.at[pos].set(tbin, mode="drop"),
                split_gain=rec.split_gain.at[pos].set(
                    jnp.where(active, top_gain, 0.0), mode="drop"),
                split_default_left=rec.split_default_left.at[pos].set(
                    dleft, mode="drop"),
                split_is_cat=rec.split_is_cat.at[pos].set(
                    iscat, mode="drop"),
                split_cat_words=rec.split_cat_words.at[pos].set(
                    catw, mode="drop"),
                internal_value=rec.internal_value.at[pos].set(
                    parent_out, mode="drop"),
                internal_count=rec.internal_count.at[pos].set(
                    state.leaf_count[wl], mode="drop"),
            )

            # 6. per-leaf aggregate updates (left child keeps parent id)
            child_depth = state.leaf_depth[wl] + 1

            def upd(arr, lvals, rvals):
                arr = arr.at[wl_s].set(lvals, mode="drop")
                return arr.at[new_s].set(rvals, mode="drop")

            leaf_output = upd(state.leaf_output, lo, ro)
            # proxy mode: lcnt_x/rcnt_x are the partition-mask EXACT
            # counts, so per-leaf bookkeeping (and the model file's
            # leaf_count/internal_count) matches the exact path
            leaf_count = upd(state.leaf_count, lcnt_x, rcnt_x)
            leaf_sum_g = upd(state.leaf_sum_g, lg, rg)
            leaf_sum_h = upd(state.leaf_sum_h, lh, rh)
            leaf_depth = upd(state.leaf_depth, child_depth, child_depth)

            # 7. best splits for the 2W children
            hists2 = jnp.concatenate([hist_left, hist_right], axis=0)
            sg2 = jnp.concatenate([lg, rg])
            sh2 = jnp.concatenate([lh, rh])
            nd2 = jnp.concatenate([lcnt_x, rcnt_x])
            can2 = jnp.concatenate([active & depth_ok(child_depth)] * 2)
            res = split_fn(hists2, sg2, sh2, nd2, feature_mask, can2,
                           meta)
            gain2 = jnp.where(jnp.isfinite(res.gain), res.gain,
                              KMIN_SCORE)
            idx2 = jnp.concatenate([wl_s, new_s])
            act2 = jnp.concatenate([active] * 2)

            st = lambda tbl, v: _store_batch(tbl, idx2, v, act2)
            state = state._replace(
                leaf_ids=leaf_ids,
                hist=pool,
                t_gain=st(state.t_gain, gain2),
                t_feature=st(state.t_feature, res.feature),
                t_bin=st(state.t_bin, res.threshold_bin),
                t_default_left=st(state.t_default_left, res.default_left),
                t_left_output=st(state.t_left_output, res.left_output),
                t_right_output=st(state.t_right_output, res.right_output),
                t_left_count=st(state.t_left_count, res.left_count),
                t_right_count=st(state.t_right_count, res.right_count),
                t_left_sum_g=st(state.t_left_sum_g, res.left_sum_g),
                t_left_sum_h=st(state.t_left_sum_h, res.left_sum_h),
                t_right_sum_g=st(state.t_right_sum_g, res.right_sum_g),
                t_right_sum_h=st(state.t_right_sum_h, res.right_sum_h),
                t_is_cat=st(state.t_is_cat, res.is_cat),
                t_cat_words=st(state.t_cat_words, res.cat_words),
                leaf_output=leaf_output,
                leaf_count=leaf_count,
                leaf_sum_g=leaf_sum_g,
                leaf_sum_h=leaf_sum_h,
                leaf_depth=leaf_depth,
                num_leaves=state.num_leaves + n_act,
                n_splits=state.n_splits + n_act,
                go_on=(n_act > 0) & (state.num_leaves + n_act < L),
                rec=rec,
            )
            return state

        # ---- forced-split prefix (ForceSplits) ----
        # Each forced split is applied like a single-slot wave with the
        # (feature, bin) CHOSEN instead of elected; children then get
        # their gain tables so gain-driven growth continues from leaf
        # numbering identical to the reference's BFS application.
        # (This intentionally mirrors body() steps 3-7 with the
        # election replaced — keep the two in sync.)
        for fs_leaf, fs_feat, fs_bin in cfg.forced:
            wl = jnp.concatenate([jnp.full(1, fs_leaf, jnp.int32),
                                  jnp.full(W - 1, -1, jnp.int32)])
            new_ids = jnp.concatenate(
                [state.num_leaves[None].astype(jnp.int32),
                 jnp.full(W - 1, -1, jnp.int32)])
            feat = jnp.full(W, fs_feat, jnp.int32)
            tbin = jnp.full(W, fs_bin, jnp.int32)
            dleft = jnp.zeros(W, bool)
            active = wl >= 0
            iscat0 = jnp.zeros(W, bool)
            catw0 = jnp.zeros((W, 8), jnp.int32)
            leaf_ids = partition_fn(bins_t, state.leaf_ids, wl, new_ids,
                                    feat, tbin, dleft, active, meta,
                                    iscat0, catw0)
            # left child keeps the parent id: histogram it directly,
            # sibling by subtraction (sizes don't matter here)
            hist_left = dq(hist_reduce_fn(
                call_hist(hsrc, bag_mask_ids(leaf_ids), wl)))
            parent_hist = state.hist[wl]
            hist_right = parent_hist - hist_left
            wl_s = jnp.where(active, wl, L)
            new_s = jnp.where(active, new_ids, L)
            pool = state.hist.at[wl_s].set(hist_left, mode="drop")
            pool = pool.at[new_s].set(hist_right, mode="drop")
            # child sums from any one feature's bins (every row lands
            # in exactly one bin per feature)
            lg = hist_left[:, 0, :, 0].sum(axis=1)
            lh = hist_left[:, 0, :, 1].sum(axis=1)
            lcnt = hist_left[:, 0, :, 2].sum(axis=1)
            rg = state.leaf_sum_g[wl] - lg
            rh = state.leaf_sum_h[wl] - lh
            rcnt = state.leaf_count[wl] - lcnt
            parent_out = calculate_leaf_output(
                state.leaf_sum_g[wl], state.leaf_sum_h[wl],
                hp.lambda_l1, hp.lambda_l2, hp.max_delta_step)
            # real gain like the reference's GatherInfoForThreshold:
            # children's split gains minus the parent's
            from .split import leaf_split_gain
            forced_gain = (
                leaf_split_gain(lg, lh + 1e-15, hp.lambda_l1,
                                hp.lambda_l2, hp.max_delta_step)
                + leaf_split_gain(rg, rh + 1e-15, hp.lambda_l1,
                                  hp.lambda_l2, hp.max_delta_step)
                - leaf_split_gain(state.leaf_sum_g[wl],
                                  state.leaf_sum_h[wl] + 2e-15,
                                  hp.lambda_l1, hp.lambda_l2,
                                  hp.max_delta_step))
            pos = jnp.where(active, state.n_splits, L - 1)
            rec = state.rec
            rec = rec._replace(
                num_leaves=rec.num_leaves + 1,
                split_leaf=rec.split_leaf.at[pos].set(wl, mode="drop"),
                split_feature=rec.split_feature.at[pos].set(
                    feat, mode="drop"),
                split_bin=rec.split_bin.at[pos].set(tbin, mode="drop"),
                split_gain=rec.split_gain.at[pos].set(
                    forced_gain, mode="drop"),
                split_default_left=rec.split_default_left.at[pos].set(
                    dleft, mode="drop"),
                internal_value=rec.internal_value.at[pos].set(
                    parent_out, mode="drop"),
                internal_count=rec.internal_count.at[pos].set(
                    state.leaf_count[wl], mode="drop"),
            )
            child_depth = state.leaf_depth[wl] + 1

            def updf(arr, lv, rv):
                arr = arr.at[wl_s].set(lv, mode="drop")
                return arr.at[new_s].set(rv, mode="drop")
            # empty-child guard: the reference refuses degenerate
            # forced splits (ForceSplits count checks); here the empty
            # side just gets a zero output instead of -0/0 = NaN
            lo = jnp.where(lcnt > 0, calculate_leaf_output(
                lg, lh + 1e-15, hp.lambda_l1, hp.lambda_l2,
                hp.max_delta_step), 0.0)
            ro = jnp.where(rcnt > 0, calculate_leaf_output(
                rg, rh + 1e-15, hp.lambda_l1, hp.lambda_l2,
                hp.max_delta_step), 0.0)
            hists2 = jnp.concatenate([hist_left, hist_right], axis=0)
            sg2 = jnp.concatenate([lg, rg])
            sh2 = jnp.concatenate([lh, rh])
            nd2 = jnp.concatenate([lcnt, rcnt])
            can2 = jnp.concatenate([active & depth_ok(child_depth)] * 2)
            res = split_fn(hists2, sg2, sh2, nd2, feature_mask, can2,
                           meta)
            gain2 = jnp.where(jnp.isfinite(res.gain), res.gain,
                              KMIN_SCORE)
            idx2 = jnp.concatenate([wl_s, new_s])
            act2 = jnp.concatenate([active] * 2)
            st = lambda tbl, v: _store_batch(tbl, idx2, v, act2)
            state = state._replace(
                leaf_ids=leaf_ids,
                hist=pool,
                t_gain=st(state.t_gain, gain2),
                t_feature=st(state.t_feature, res.feature),
                t_bin=st(state.t_bin, res.threshold_bin),
                t_default_left=st(state.t_default_left,
                                  res.default_left),
                t_left_output=st(state.t_left_output, res.left_output),
                t_right_output=st(state.t_right_output,
                                  res.right_output),
                t_left_count=st(state.t_left_count, res.left_count),
                t_right_count=st(state.t_right_count, res.right_count),
                t_left_sum_g=st(state.t_left_sum_g, res.left_sum_g),
                t_left_sum_h=st(state.t_left_sum_h, res.left_sum_h),
                t_right_sum_g=st(state.t_right_sum_g, res.right_sum_g),
                t_right_sum_h=st(state.t_right_sum_h, res.right_sum_h),
                t_is_cat=st(state.t_is_cat, res.is_cat),
                t_cat_words=st(state.t_cat_words, res.cat_words),
                leaf_output=updf(state.leaf_output, lo, ro),
                leaf_count=updf(state.leaf_count, lcnt, rcnt),
                leaf_sum_g=updf(state.leaf_sum_g, lg, rg),
                leaf_sum_h=updf(state.leaf_sum_h, lh, rh),
                leaf_depth=updf(state.leaf_depth, child_depth,
                                child_depth),
                num_leaves=state.num_leaves + 1,
                n_splits=state.n_splits + 1,
                rec=rec,
            )

        state = jax.lax.while_loop(lambda s: s.go_on, body, state)
        rec = state.rec._replace(
            leaf_output=state.leaf_output,
            leaf_count=state.leaf_count,
            leaf_sum_g=state.leaf_sum_g,
            leaf_sum_h=state.leaf_sum_h,
        )
        return rec, state.leaf_ids

    # jit-capture: ok(B, hp, cfg, quant, use_fused, use_fused_xla,
    # fused_chunk, fused_interpret, gpu_hist, fused_kernel_fn,
    # fused_partition_histogram_xla, meta_const,
    # bound_counts, depth_ok, hist_fn, hist_reduce_fn, reduce_fn,
    # max_reduce_fn, row_offset_fn, split_fn, partition_fn) —
    # factory-scoped jit: every capture derives from this factory
    # call's WaveGrowerConfig/meta/seam callables. meta_const is the
    # LEGACY 5-arg fallback only; registry-path callers pass meta as
    # the traced 6th argument (PR 5), and the step-cache geometry key
    # covers cfg + the meta signature, so a registry hit can never
    # see another booster's meta_const.
    return jax.jit(grow) if jit else grow


def apply_wave_splits(bins_t, leaf_ids, wl, new_ids, feat, tbin, dleft,
                      active, meta: FeatureMeta, iscat=None, catw=None):
    """Apply up to W splits to the row partition in one fused pass.

    For each wave slot k: rows with ``leaf_ids == wl[k]`` whose binned
    feature value goes right move to ``new_ids[k]``
    (DataPartition::Split + Bin::Split semantics,
    src/treelearner/data_partition.hpp:109-166). ``iscat``/``catw``
    carry per-slot categorical flags + left-set bitsets.
    """
    W = wl.shape[0]
    out = leaf_ids
    for k in range(W):
        col = member_column(bins_t, feat[k], meta)   # EFB-decoded
        right = row_goes_right(
            col, tbin[k], dleft[k],
            meta.missing_type[feat[k]], meta.default_bin[feat[k]],
            meta.num_bin[feat[k]],
            is_cat=(False if iscat is None else iscat[k]),
            cat_words=(None if catw is None else catw[k]))
        move = (leaf_ids == wl[k]) & right & active[k]
        out = jnp.where(move, new_ids[k], out)
    return out
