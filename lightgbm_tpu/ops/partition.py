"""Leaf-membership partition updates.

TPU-native counterpart of DataPartition::Split + Bin::Split
(reference: src/treelearner/data_partition.hpp:109-166,
src/io/dense_bin.hpp Split). The reference maintains a permutation array
with per-leaf (begin, count) ranges; on TPU we keep a flat ``leaf_ids[N]``
assignment updated by a masked elementwise select — shape-static, no
host round trip, and directly usable to scatter leaf outputs into the
score vector.

Categorical splits carry a bin-space bitset (set bit = bin goes LEFT,
src/io/dense_bin.hpp SplitCategorical semantics): membership is an
8-way word select + bit test, all lane-parallel.
"""
from __future__ import annotations

import jax.numpy as jnp

from .split import MISSING_NAN, MISSING_ZERO, NCAT_WORDS


def member_column(bins_t, feat, meta):
    """Fetch feature ``feat``'s bin column, decoding EFB bundles
    (io/efb.py): in the member's range -> col - offset, outside (another
    member active / all-default) -> the member's default bin. Compiles
    to a plain row fetch when the dataset is unbundled."""
    if jnp.ndim(meta.bundle) == 0:
        return bins_t[feat].astype(jnp.int32)
    col = bins_t[meta.bundle[feat]].astype(jnp.int32)
    off = meta.offset[feat]
    nb = meta.num_bin[feat]
    return jnp.where((col >= off) & (col < off + nb), col - off,
                     meta.default_bin[feat])


def cat_bit_left(bin_col, cat_words):
    """True where the bin's bit is set in the left-set bitset.

    bin_col: [N] int32; cat_words: [NCAT_WORDS] int32.
    """
    widx = jnp.right_shift(bin_col, 5)
    word = jnp.zeros_like(bin_col)
    for k in range(NCAT_WORDS):
        word = jnp.where(widx == k, cat_words[k], word)
    bit = jnp.bitwise_and(
        jnp.right_shift(word, jnp.bitwise_and(bin_col, 31)), 1)
    return bit != 0


def row_goes_right(bin_col, threshold_bin, default_left, missing_type,
                   default_bin, num_bin, is_cat=False, cat_words=None):
    """Binned decision for one split (dense_bin.hpp Split semantics).

    - missing NaN  -> rows in the NaN bin (num_bin-1) go to the default side
    - missing Zero -> rows in the default(zero) bin go to the default side
    - otherwise    -> bin <= threshold goes left
    - categorical  -> bin's bit set in ``cat_words`` goes left; unseen
      and NaN bins have no bit and go right (dense_bin.hpp:SplitCat)
    """
    is_missing = (((missing_type == MISSING_NAN) & (bin_col == num_bin - 1))
                  | ((missing_type == MISSING_ZERO) & (bin_col == default_bin)))
    base_right = bin_col > threshold_bin
    right = jnp.where(is_missing, ~default_left, base_right)
    if cat_words is not None:
        right = jnp.where(is_cat, ~cat_bit_left(bin_col, cat_words),
                          right)
    return right


def apply_split(leaf_ids, bin_col, leaf, new_leaf, threshold_bin,
                default_left, missing_type, default_bin, num_bin,
                enabled=True, is_cat=False, cat_words=None):
    """Send the split leaf's right-side rows to ``new_leaf``.

    Left child keeps the parent's leaf index, right child takes the new
    index — matching Tree::Split leaf numbering (src/io/tree.cpp: left
    keeps ``leaf``, right becomes ``num_leaves_``).
    """
    right = row_goes_right(bin_col, threshold_bin, default_left,
                           missing_type, default_bin, num_bin,
                           is_cat=is_cat, cat_words=cat_words)
    move = (leaf_ids == leaf) & right & enabled
    return jnp.where(move, new_leaf, leaf_ids)
