"""Leaf-membership partition updates.

TPU-native counterpart of DataPartition::Split + Bin::Split
(reference: src/treelearner/data_partition.hpp:109-166,
src/io/dense_bin.hpp Split). The reference maintains a permutation array
with per-leaf (begin, count) ranges; on TPU we keep a flat ``leaf_ids[N]``
assignment updated by a masked elementwise select — shape-static, no
host round trip, and directly usable to scatter leaf outputs into the
score vector.
"""
from __future__ import annotations

import jax.numpy as jnp

from .split import MISSING_NAN, MISSING_ZERO


def row_goes_right(bin_col, threshold_bin, default_left, missing_type,
                   default_bin, num_bin):
    """Binned decision for one split (dense_bin.hpp Split semantics).

    - missing NaN  -> rows in the NaN bin (num_bin-1) go to the default side
    - missing Zero -> rows in the default(zero) bin go to the default side
    - otherwise    -> bin <= threshold goes left
    """
    is_missing = (((missing_type == MISSING_NAN) & (bin_col == num_bin - 1))
                  | ((missing_type == MISSING_ZERO) & (bin_col == default_bin)))
    base_right = bin_col > threshold_bin
    return jnp.where(is_missing, ~default_left, base_right)


def apply_split(leaf_ids, bin_col, leaf, new_leaf, threshold_bin,
                default_left, missing_type, default_bin, num_bin,
                enabled=True):
    """Send the split leaf's right-side rows to ``new_leaf``.

    Left child keeps the parent's leaf index, right child takes the new
    index — matching Tree::Split leaf numbering (src/io/tree.cpp: left
    keeps ``leaf``, right becomes ``num_leaves_``).
    """
    right = row_goes_right(bin_col, threshold_bin, default_left,
                           missing_type, default_bin, num_bin)
    move = (leaf_ids == leaf) & right & enabled
    return jnp.where(move, new_leaf, leaf_ids)
