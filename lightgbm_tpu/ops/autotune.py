"""Kernel autotuner + persistent tuning/compile caches for the Pallas
hot paths.

The engine has two Pallas hot paths — the fused partition+histogram
training kernel (ops/hist_wave.py) and the fused forest prediction
kernel (ops/stacked_predict.py) — and both are tiled: rows stream
through the training kernels in ``chunk``-row grid steps, prediction
rows in ``row_tile`` blocks of ``tc`` trees. The best tiling depends on
the (features, bins, dtype-tier, device) shape in exactly the way the
reference's own tuning guide documents for its GPU kernels
(docs/GPU-Performance.rst max_bin/workgroup trade-offs); one hardcoded
tile cannot serve arbitrary shapes.

This module is the single place that knows about tiles:

1. **Shared VMEM geometry.** ``hist_geometry`` / the ``*_block_shapes``
   functions compute the exact VMEM block shapes the kernels' BlockSpecs
   are built from, and the ``*_vmem_bytes`` predicates price those SAME
   shapes (double-buffering grid-indexed blocks, adding the in-kernel
   temporaries). The kernels import their shapes from here, so the
   VMEM-fit guards can never drift from what the kernels allocate.
2. **The autotuner.** On first encounter of a (kernel, n_features,
   n_bins, dtype-tier, device-kind) key, ``Autotuner.best`` times a
   small VMEM-feasible candidate set (median-of-k wall time with a
   device-sync readback, utils/timing.py) and persists the winner to a
   versioned JSON cache on disk — the same versioned-token discipline
   as the dataset binary cache (io/dataset.py BINARY_TOKEN): a version
   mismatch re-tunes instead of trusting stale entries.
3. **The persistent XLA compile cache.** ``ensure_compile_cache`` wires
   jax's compilation cache (idempotent, never overriding an explicit
   operator setting), so repeated runs skip both the tuning sweep AND
   recompilation.

Config surface: ``tpu_autotune`` (on / off / exhaustive) and
``tpu_tuning_cache`` (cache file path; empty = the shared cache dir,
io/dataset.py ``default_cache_dir``). Tuning only ever runs on a real
TPU backend — CPU/interpret callers get the defaults for free.
"""
from __future__ import annotations

import functools
import json
import math
import os
from typing import Callable, Dict, List, Optional

from ..obs import registry as obs
from ..utils import log, timing

# ---------------------------------------------------------------------------
# Shared VMEM constants and kernel block geometry
# ---------------------------------------------------------------------------

# scoped-VMEM cap passed to every Pallas hot-path kernel (CompilerParams
# vmem_limit_bytes): the unrolled group loops' temporaries exceed the
# 16 MB default; v5e has 128 MB physical VMEM
PALLAS_VMEM_LIMIT_BYTES = 100 * 1024 * 1024
# working-set budget the tile guards/tuner admit against: headroom under
# the limit for Mosaic's own temporaries
PALLAS_VMEM_BUDGET_BYTES = 72 * 1024 * 1024

# default tiles (the pre-autotuner hardcoded values, kept as the
# fallback for tpu_autotune=off, CPU backends and interpret mode)
DEFAULT_HIST_CHUNK = 8192
DEFAULT_HIST_CHUNK_INT8 = 16384
# largest row chunk any candidate set can offer (the exhaustive tier's
# ceiling) — sharded ingest aligns its shards against THIS bound so
# grower pad adoption (models/gbdt.py) holds for every tunable chunk
MAX_HIST_CHUNK = 65536
DEFAULT_ROW_TILE = 2048


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _nelem(shape) -> int:
    return int(math.prod(shape))


def hist_geometry(*, F: int, B: int, W: int, F_rows: Optional[int] = None
                  ) -> Dict[str, int]:
    """Histogram-kernel tile geometry shared by BOTH wave kernels and
    the VMEM predicates: per-feature bin rows are padded to the
    8-aligned sublane stride Bp, ``group_sz`` features share one
    128-row matmul M-tile, and the accumulator rows pad to gb_pad.
    ``F_rows`` is the HBM bin-matrix row count (ceil(F/2) when 4-bit
    packed)."""
    Bp = _round_up(B, 8)
    group_sz = max(1, 128 // Bp)
    gb = group_sz * Bp
    groups = -(-F // group_sz)
    return dict(Bp=Bp, group_sz=group_sz, gb=gb, groups=groups,
                gb_pad=_round_up(gb, 128), wp=_round_up(W, 8),
                F_rows=F if F_rows is None else F_rows)


def wave_hist_block_shapes(*, chunk: int, geom: Dict[str, int]
                           ) -> Dict[str, tuple]:
    """VMEM block shapes of wave_histogram_pallas — the kernel's
    BlockSpecs are built from THESE tuples."""
    return {
        "wl": (geom["wp"], 1),                            # f32 const
        "bins": (geom["F_rows"], chunk),                  # grid-indexed
        "ghl": (4, chunk),                                # grid-indexed
        "hist": (geom["groups"], geom["gb_pad"], 128),    # accumulator
    }


def fused_hist_block_shapes(*, chunk: int, geom: Dict[str, int],
                            tbl_rows: int) -> Dict[str, tuple]:
    """VMEM block shapes of fused_partition_histogram_pallas."""
    return {
        "tbl": (128, tbl_rows),                           # i32 const
        "bins": (geom["F_rows"], chunk),                  # grid-indexed
        "ghm": (4, chunk),                                # grid-indexed
        "leaf": (1, chunk),                               # grid-indexed
        "hist": (geom["groups"], geom["gb_pad"], 128),    # accumulator
        "leaf_out": (1, chunk),                           # grid-indexed
        "cnt": (geom["wp"], 128),                         # accumulator
    }


def hist_vmem_bytes(*, chunk: int, geom: Dict[str, int], W: int,
                    fused: bool, bins_bytes: int = 1, int8: bool = False,
                    count_proxy: bool = False,
                    tbl_rows: Optional[int] = None,
                    variant: Optional[str] = None) -> int:
    """Working-set bytes of one grid step of a wave-histogram kernel,
    priced from the SAME block shapes the BlockSpecs use: grid-indexed
    blocks double-buffered, plus the in-kernel temporaries (the
    transposed one-hot tile, the 128-row weight matrix, one matmul
    accumulator, and — fused — the [W, chunk] partition intermediates).
    ``variant="hilo4"`` adds the second histogram-shaped count
    accumulator (and its per-group matmul result) the exact-tier
    count dot writes.
    """
    oh_bytes = 1 if int8 else 2                  # int8 / bf16 one-hot
    acc_bytes = 4                                # i32 / f32 accumulator
    if fused:
        if tbl_rows is None:
            # the kernel's split-table row count is the kernel's to
            # define (lazy: hist_wave imports this module at top level)
            from .hist_wave import TBL_ROWS
            tbl_rows = TBL_ROWS
        s = fused_hist_block_shapes(chunk=chunk, geom=geom,
                                    tbl_rows=tbl_rows)
        b = (2 * _nelem(s["bins"]) * bins_bytes
             + 2 * _nelem(s["ghm"]) * 4
             + 2 * _nelem(s["leaf"]) * 4
             + 2 * _nelem(s["leaf_out"]) * 4
             + _nelem(s["tbl"]) * 4
             + _nelem(s["hist"]) * acc_bytes
             + (_nelem(s["cnt"]) * 4 if count_proxy else 0))
        # partition temporaries: cols / sentinel compares / moved, all
        # [W, chunk] i32-grade, ~4 live at once
        b += 4 * W * chunk * 4
    else:
        s = wave_hist_block_shapes(chunk=chunk, geom=geom)
        b = (2 * _nelem(s["bins"]) * bins_bytes
             + 2 * _nelem(s["ghl"]) * 4
             + _nelem(s["wl"]) * 4
             + _nelem(s["hist"]) * acc_bytes)
    b += (geom["gb"] * chunk * oh_bytes          # one-hot tile
          + 128 * chunk * 4                      # weight rows
          + geom["gb_pad"] * 128 * acc_bytes)    # per-group matmul acc
    if variant == "hilo4":
        # the count dot's accumulator ref + per-group result + the
        # f32 membership rows it contracts against
        b += (_nelem((geom["groups"], geom["gb_pad"], 128)) * 4
              + geom["gb_pad"] * 128 * 4
              + 128 * chunk * 4)
    return b


def forest_block_shapes(*, F: int, Wtot: int, TC: int, Sp: int, Lp: int,
                        K: int, row_tile: int) -> Dict[str, tuple]:
    """VMEM block shapes of the fused forest prediction kernel
    (ops/stacked_predict.py forest_predict_pallas) — its BlockSpecs are
    built from THESE tuples, and _pallas_tc prices the same ones."""
    return {
        "codes": (F, row_tile),                  # i32, row-indexed
        "W": (1, Wtot, TC * Sp),                 # i8, step-indexed
        "P": (1, TC, Sp, Lp),                    # i8, step-indexed
        "tgt": (1, TC, Lp),                      # i32, step-indexed
        "leaf": (1, TC, Lp),                     # f32, step-indexed
        "cls": (1, TC, K),                       # f32, step-indexed
        "acc": (row_tile, K),                    # f32 accumulator
    }


def forest_vmem_bytes(*, F: int, Wtot: int, TC: int, Sp: int, Lp: int,
                      K: int, row_tile: int) -> int:
    """Working-set bytes of one fused-forest grid step: the
    double-buffered step-indexed blocks plus the in-kernel temporaries
    (one-hot tile [Wtot, nt] i8, C int32 + C8 int8 [nt, TC*Sp],
    per-tree E [nt, Lp] i32)."""
    s = forest_block_shapes(F=F, Wtot=Wtot, TC=TC, Sp=Sp, Lp=Lp, K=K,
                            row_tile=row_tile)
    return (2 * _nelem(s["W"])                   # int8, dbl-buffered
            + 2 * _nelem(s["P"])                 # int8, dbl-buffered
            + 2 * _nelem(s["tgt"]) * 4
            + 2 * _nelem(s["leaf"]) * 4
            + 2 * _nelem(s["cls"]) * 4
            + 2 * _nelem(s["codes"]) * 4
            + _nelem(s["acc"]) * 4
            + Wtot * row_tile                    # one-hot tile (i8)
            + row_tile * TC * Sp * 5             # C (i32) + C8 (i8)
            + row_tile * Lp * 4)                 # per-tree E (i32)


def fits_vmem(nbytes: int) -> bool:
    return nbytes <= PALLAS_VMEM_BUDGET_BYTES


def tpu_compiler_params(*, vmem_limit_bytes: int = PALLAS_VMEM_LIMIT_BYTES):
    """Version-portable pltpu CompilerParams (renamed from
    TPUCompilerParams after jax 0.4.x)."""
    from jax.experimental.pallas import tpu as pltpu
    cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")
    return cls(vmem_limit_bytes=vmem_limit_bytes)


# ---------------------------------------------------------------------------
# GPU (Pallas-Triton) tier geometry: shared-memory pricing, per-CTA tiles
# ---------------------------------------------------------------------------

# per-CTA shared-memory budget the GPU tile guards admit against — the
# smem role VMEM plays on TPU, with the same headroom discipline: 48 KB
# is the portable static-smem floor every supported CUDA arch provides
# without opt-in dynamic carve-outs, and the Triton compiler's own
# reduction scratch must fit beside our blocks
GPU_SMEM_LIMIT_BYTES = 48 * 1024
GPU_SMEM_BUDGET_BYTES = 40 * 1024

# default per-CTA row tile of the GPU histogram kernels (the role
# DEFAULT_HIST_CHUNK plays on TPU; the histogram itself accumulates in
# global memory via atomics, so the row tile prices only the streamed
# bins/gradient blocks — much smaller tiles than the TPU's 8k/16k
# VMEM-resident chunks)
DEFAULT_GPU_HIST_CHUNK = 1024
DEFAULT_GPU_ROW_TILE = 1024


def gpu_hist_block_shapes(*, chunk: int, geom: Dict[str, int],
                          fused: bool, tbl_rows: Optional[int] = None
                          ) -> Dict[str, tuple]:
    """Per-CTA block shapes of the GPU wave/fused histogram kernels —
    their BlockSpecs are built from THESE tuples (same can't-drift
    contract as wave_hist_block_shapes on TPU). The histogram output
    lives in global memory (atomic accumulation), so only the streamed
    row blocks and the small split tables are priced."""
    s = {
        "wl": (geom["wp"],),                              # i32 const
        "bins": (geom["F_rows"], chunk),                  # grid-indexed
        "gh": (2, chunk),                                 # grid-indexed
    }
    if fused:
        if tbl_rows is None:
            from .hist_wave import TBL_ROWS
            tbl_rows = TBL_ROWS
        s["tbl"] = (tbl_rows, geom["wp"])                 # i32 const
        s["mask"] = (chunk,)                              # grid-indexed
        s["leaf"] = (chunk,)                              # grid-indexed
        s["leaf_out"] = (chunk,)                          # grid-indexed
    return s


def gpu_hist_smem_bytes(*, chunk: int, geom: Dict[str, int], fused: bool,
                        bins_bytes: int = 1,
                        tbl_rows: Optional[int] = None) -> int:
    """Working-set bytes of one GPU histogram CTA, priced from the SAME
    block shapes the BlockSpecs use plus the per-row temporaries (the
    [F] flat-index/value vectors of the atomic scatter)."""
    s = gpu_hist_block_shapes(chunk=chunk, geom=geom, fused=fused,
                              tbl_rows=tbl_rows)
    b = (_nelem(s["bins"]) * bins_bytes
         + _nelem(s["gh"]) * 4
         + _nelem(s["wl"]) * 4)
    if fused:
        b += (_nelem(s["tbl"]) * 4
              + _nelem(s["mask"]) * 4
              + 2 * _nelem(s["leaf"]) * 4)
    # per-row scatter temporaries: [F] i32 flat indices + [F] f32 vals
    # per channel (3 channels), plus the [W] slot-compare vector
    b += geom["F_rows"] * 4 * 4 + geom["wp"] * 4
    return b


def fits_smem(nbytes: int) -> bool:
    return nbytes <= GPU_SMEM_BUDGET_BYTES


def gpu_compiler_params(*, num_warps: int = 4, num_stages: int = 2):
    """Version-portable Pallas-Triton CompilerParams, or None when the
    Triton lowering is absent (interpret-mode callers pass None)."""
    try:
        from jax.experimental.pallas import triton as plgpu
    except ImportError:
        return None
    cls = getattr(plgpu, "CompilerParams", None) \
        or getattr(plgpu, "TritonCompilerParams", None)
    if cls is None:
        return None
    return cls(num_warps=num_warps, num_stages=num_stages)


@functools.lru_cache(maxsize=1)
def gpu_pallas_supported() -> bool:
    """Is the Pallas-Triton lowering importable in this jax? Gates the
    pallas-gpu route (tune_hist_route) and the gpu_tier test module's
    clean skip — capability, not device presence (interpret-mode parity
    runs on any backend)."""
    try:
        from jax.experimental.pallas import triton  # noqa: F401
        return True
    except Exception:       # noqa: BLE001 — absent lowering = no route
        return False


# the capability ladder of the histogram hot loop, best-first; the
# chosen rung rides WaveGrowerConfig.route into the step-cache geometry
# key (different backends = different compiled programs)
HIST_ROUTES = ("pallas-tpu", "pallas-gpu", "fused-xla", "two-pass")


def tune_hist_route(*, backend: Optional[str] = None,
                    use_pallas: Optional[bool] = None,
                    fused_eligible: bool = True) -> str:
    """The histogram hot-loop route for this backend, by capability:
    the device's own Pallas tier when it can lower ("pallas-tpu" /
    "pallas-gpu" — the Triton rung additionally needs the Pallas-Triton
    lowering importable), else the fused single-pass XLA kernel, else
    the legacy two-pass partition+histogram. ``use_pallas`` is the
    config override (None = auto); ``fused_eligible`` is the caller's
    structural gate (default kernel seams, no EFB bundles, no sparse
    tier — ops/wave_grower.py owns it)."""
    from ..utils.device import backend_kind
    b = backend or backend_kind()
    pallas = use_pallas if use_pallas is not None else (
        b == "tpu" or (b == "gpu" and gpu_pallas_supported()))
    if pallas:
        return "pallas-gpu" if b == "gpu" else "pallas-tpu"
    if fused_eligible:
        return "fused-xla"
    return "two-pass"


# ---------------------------------------------------------------------------
# Tuning cache (versioned JSON on disk)
# ---------------------------------------------------------------------------

TUNING_CACHE_VERSION = 1


def default_tuning_cache_path() -> str:
    from ..io.dataset import default_cache_dir
    return os.path.join(default_cache_dir(),
                        f"tuning_v{TUNING_CACHE_VERSION}.json")


class TuningCache:
    """{key -> {choice, timings_ms}} persisted as versioned JSON.

    Likes the dataset binary cache's versioned token (io/dataset.py):
    a file whose ``version`` field doesn't match this reader is ignored
    wholesale (re-tune), never partially trusted. Writes are atomic
    (tmp + rename) so concurrent trainers at worst re-tune."""

    def __init__(self, path: str):
        self.path = path
        self._entries: Optional[Dict[str, dict]] = None

    @staticmethod
    def key_string(kernel: str, key: Dict) -> str:
        return json.dumps({"kernel": kernel, **key}, sort_keys=True)

    def _load(self) -> Dict[str, dict]:
        if self._entries is None:
            self._entries = {}
            try:
                with open(self.path) as fh:
                    d = json.load(fh)
                if (isinstance(d, dict)
                        and d.get("version") == TUNING_CACHE_VERSION
                        and isinstance(d.get("entries"), dict)):
                    self._entries = d["entries"]
                else:
                    log.debug("tuning cache %s has version %r (want %d); "
                              "ignoring it", self.path,
                              d.get("version") if isinstance(d, dict)
                              else None, TUNING_CACHE_VERSION)
            except (OSError, ValueError):
                pass
        return self._entries

    def get(self, key: str) -> Optional[dict]:
        return self._load().get(key)

    def put(self, key: str, record: dict) -> None:
        entries = self._load()
        entries[key] = record
        try:
            from ..utils.fileio import atomic_write
            with atomic_write(self.path) as fh:
                json.dump({"version": TUNING_CACHE_VERSION,
                           "entries": entries}, fh, indent=1)
        except OSError as e:
            log.warning("could not persist tuning cache %s: %s",
                        self.path, e)


# ---------------------------------------------------------------------------
# The autotuner
# ---------------------------------------------------------------------------

class Autotuner:
    """Times candidate tile configurations once per key, then serves the
    winner from the on-disk cache forever."""

    def __init__(self, mode: str = "on",
                 cache_path: Optional[str] = None):
        if mode not in ("on", "off", "exhaustive"):
            log.warning("tpu_autotune=%r is not one of on/off/exhaustive;"
                        " using 'on'", mode)
            mode = "on"
        self.mode = mode
        self.cache = TuningCache(cache_path or default_tuning_cache_path())

    def best(self, kernel: str, key: Dict, candidates: List[dict],
             measure: Callable[[dict], float],
             default: Optional[dict] = None) -> dict:
        """The winning candidate for ``key``.

        ``candidates``: JSON-able config dicts (already VMEM-filtered).
        ``measure(candidate) -> seconds`` (the median-of-k repeat count
        lives in the caller's harness, timing.measure). A cached choice
        is only honored while it is still a member of the current
        candidate set — a changed candidate generation (new VMEM
        budget, new kernel rev bumping TUNING_CACHE_VERSION) re-tunes.
        Callers whose candidate sets vary with non-key inputs must fold
        a candidate fingerprint into ``key``, or differently-shaped
        runs would perpetually overwrite each other's entries.
        Candidates that fail to compile or run are skipped, not
        fatal."""
        if not candidates:
            return default
        if self.mode == "off":
            return default if default is not None else candidates[0]
        ck = self.cache.key_string(kernel, key)
        hit = self.cache.get(ck)
        if hit is not None and hit.get("choice") in candidates:
            obs.counter("autotune/cache_hits").add(1)
            return hit["choice"]
        timings_ms: Dict[str, float] = {}
        best_c, best_t = None, float("inf")
        with timing.phase(f"autotune/{kernel}"):
            for cand in candidates:
                try:
                    t = measure(cand)
                except Exception as e:        # noqa: BLE001 — a candidate
                    # that Mosaic rejects must not kill training
                    log.debug("autotune[%s]: candidate %s failed: %s",
                              kernel, cand, e)
                    continue
                timings_ms[json.dumps(cand, sort_keys=True)] = round(
                    t * 1e3, 4)
                if t < best_t:
                    best_c, best_t = cand, t
        if best_c is None:
            log.warning("autotune[%s]: every candidate failed; using the"
                        " default %s", kernel, default)
            return default if default is not None else candidates[0]
        self.cache.put(ck, {"choice": best_c, "timings_ms": timings_ms})
        obs.counter("autotune/tuned_keys").add(1)
        log.info("autotune[%s]: chose %s (%.3f ms; %d candidates timed)",
                 kernel, best_c, best_t * 1e3, len(timings_ms))
        return best_c


# module-level tuner, configured from Config (models/gbdt.py init);
# prediction (ops/stacked_predict.py) shares whatever was last configured
_mode = "on"
_cache_path: Optional[str] = None
_tuner: Optional[Autotuner] = None


def configure(mode: str = "on", cache_path: Optional[str] = None) -> None:
    """Install the process-wide tuning mode + cache path
    (config.tpu_autotune / config.tpu_tuning_cache)."""
    global _mode, _cache_path, _tuner
    if mode != _mode or (cache_path or None) != _cache_path:
        _mode, _cache_path = mode, (cache_path or None)
        _tuner = None


def tuner() -> Autotuner:
    global _tuner
    if _tuner is None:
        _tuner = Autotuner(_mode, _cache_path)
    return _tuner


def device_kind() -> str:
    """Cache-key device identity (e.g. 'TPU v5e' / 'cpu')."""
    from ..utils.device import get_devices
    d = get_devices()[0]
    return str(getattr(d, "device_kind", None) or d.platform)


# ---------------------------------------------------------------------------
# Persistent XLA compile cache
# ---------------------------------------------------------------------------

_compile_cache_done = False


def _jax_version() -> tuple:
    import jax
    try:
        return tuple(int(x) for x in jax.__version__.split(".")[:2])
    except (AttributeError, ValueError):
        return (0, 0)


def ensure_compile_cache(path: Optional[str] = None,
                         cpu_opt_in: bool = False,
                         mode: Optional[int] = None) -> None:
    """Wire jax's persistent compilation cache so the grower/predict
    kernels compile once per machine, not once per process (~tens of
    seconds per distinct shape on TPU). Idempotent; an explicit
    operator/test setting of jax_compilation_cache_dir is respected.

    ``mode`` is config.tpu_compile_cache's tri-state. The policy
    matrix (Design.md §5i):

    ========  ==========  =======  ========
    backend   -1 (auto)   0 (off)  1 (on)
    ========  ==========  =======  ========
    tpu       on          off      on
    gpu       on          off      on
    cpu       off         off      jax>=0.5
    ========  ==========  =======  ========

    TPU and GPU auto-enable: that is where the expensive Mosaic /
    Triton compiles live, and their deserialization paths are sound.
    The CPU backend stays opt-in because this image's jax 0.4.x
    flakily segfaults while DESERIALIZING warm CPU cache entries
    (observed ~1/3 of warm-cache test runs) — mode=1 on CPU is gated
    on jax >= 0.5 where that path is fixed; on older jax it warns and
    stays off. An operator can always set jax_compilation_cache_dir
    explicitly (it is respected on any jax and any backend).
    ``cpu_opt_in`` is the pre-rename kwarg (tpu_compile_cache_cpu),
    kept for callers that predate ``mode``: True maps to mode=1."""
    global _compile_cache_done
    if _compile_cache_done:
        return
    if mode is None:
        mode = 1 if cpu_opt_in else -1
    import jax
    try:
        _compile_cache_done = True
        if getattr(jax.config, "jax_compilation_cache_dir", None):
            return                       # operator already configured it
        from ..utils.device import backend_kind
        backend = backend_kind()
        if mode == 0 or (backend == "cpu" and mode != 1):
            # NOT a terminal decision: a later booster may opt in
            # (tpu_compile_cache=1), so leave the flag unset
            _compile_cache_done = False
            return
        if backend == "cpu" and _jax_version() < (0, 5):
            log.warning(
                "tpu_compile_cache=1 on the CPU backend needs jax >= "
                "0.5 (this jax %s flakily segfaults deserializing "
                "warm CPU cache entries); leaving the persistent "
                "compile cache off", jax.__version__)
            return
        from ..io.dataset import default_cache_dir
        jax.config.update("jax_compilation_cache_dir",
                          path or os.path.join(default_cache_dir(), "xla"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          1.0)
    except Exception as e:               # noqa: BLE001 — the cache is an
        # optimization; a jax without it must not break training
        log.debug("persistent compile cache unavailable: %s", e)


# ---------------------------------------------------------------------------
# Histogram-kernel chunk tuning (training hot path)
# ---------------------------------------------------------------------------

def hist_chunk_candidates(*, F: int, B: int, W: int, fused: bool,
                          bins_bytes: int = 1, int8: bool = False,
                          count_proxy: bool = False, packed4: bool = False,
                          n_rows: int = 0, exhaustive: bool = False,
                          variant: Optional[str] = None) -> List[dict]:
    """VMEM-feasible row-chunk candidates for the wave/fused histogram
    kernels, largest-first. Chunks beyond the dataset's rows are
    pointless (the kernel would pad the whole matrix up); the int8 tier
    additionally keeps the padded row count under the int32 histogram
    overflow guard."""
    geom = hist_geometry(F=F, B=B, W=W,
                         F_rows=(F + 1) // 2 if packed4 else F)
    base = ((1024, 2048, 4096, 8192, 16384, 32768, MAX_HIST_CHUNK)
            if exhaustive else (4096, 8192, 16384, 32768))
    out = []
    for c in base:
        if n_rows and c > max(n_rows, base[0]):
            continue
        if int8 and n_rows and 127 * (n_rows + (-n_rows) % c) >= 2 ** 31:
            continue
        if fits_vmem(hist_vmem_bytes(
                chunk=c, geom=geom, W=W, fused=fused,
                bins_bytes=bins_bytes, int8=int8,
                count_proxy=count_proxy, variant=variant)):
            out.append({"chunk": c})
    return out[::-1]


def gpu_hist_chunk_candidates(*, F: int, B: int, W: int, fused: bool,
                              bins_bytes: int = 1, packed4: bool = False,
                              n_rows: int = 0, exhaustive: bool = False
                              ) -> List[dict]:
    """Shared-memory-feasible per-CTA row tiles for the GPU histogram
    kernels, largest-first — the same candidate-guard contract as
    hist_chunk_candidates, priced by gpu_hist_smem_bytes instead of
    hist_vmem_bytes. The int8 overflow guard does not apply: the GPU
    quantized tier accumulates int32 in GLOBAL memory (per-cell atomic
    adds), not a per-chunk VMEM-resident plane."""
    geom = hist_geometry(F=F, B=B, W=W,
                         F_rows=(F + 1) // 2 if packed4 else F)
    base = ((128, 256, 512, 1024, 2048, 4096) if exhaustive
            else (256, 512, 1024, 2048))
    out = []
    for c in base:
        if n_rows and c > max(n_rows, base[0]):
            continue
        if fits_smem(gpu_hist_smem_bytes(chunk=c, geom=geom, fused=fused,
                                         bins_bytes=bins_bytes)):
            out.append({"chunk": c})
    return out[::-1]


def tune_hist_chunk(*, fused: bool, F: int, B: int, W: int,
                    precision: str = "highest", count_proxy: bool = False,
                    packed4: bool = False, any_cat: bool = False,
                    bins_bytes: int = 1, n_rows: int = 0,
                    variant: Optional[str] = None, _measure=None) -> int:
    """The row chunk the histogram hot path should run with — tuned on
    first encounter of this (kernel, F, B, tier, device) key, cached
    thereafter. On CPU (and with tpu_autotune=off) this returns the
    measured per-tier default untouched. The GPU arm tunes per-CTA row
    tiles against the shared-memory budget (gpu_hist_chunk_candidates)
    under its own kernel names, so cached TPU decisions are untouched;
    timing needs a real GPU — ``_measure`` injects a fake timer so the
    decision logic unit-tests off-GPU (it routes the GPU arm on any
    non-TPU backend)."""
    int8 = precision == "int8"
    default = DEFAULT_HIST_CHUNK_INT8 if int8 else DEFAULT_HIST_CHUNK
    t = tuner()
    from ..utils.device import backend_kind
    backend = backend_kind()
    if t.mode == "off" or (backend == "cpu" and _measure is None):
        return default
    variant = variant if precision == "highest" else None
    if backend == "gpu" or (backend != "tpu" and _measure is not None):
        cands = gpu_hist_chunk_candidates(
            F=F, B=B, W=W, fused=fused, bins_bytes=bins_bytes,
            packed4=packed4, n_rows=n_rows,
            exhaustive=t.mode == "exhaustive")
        if not cands:
            return DEFAULT_GPU_HIST_CHUNK
        if len(cands) == 1:
            return int(cands[0]["chunk"])
        tier = precision + ("+proxy" if count_proxy else "") \
            + ("+packed4" if packed4 else "")
        key = {"F": F, "B": B, "W": W, "tier": tier, "fused": fused,
               "cat": bool(any_cat), "bins_bytes": bins_bytes,
               "device": device_kind(),
               "chunks": [c["chunk"] for c in cands]}
        measure = _measure or _hist_measure_fn_gpu(
            fused=fused, F=F, B=B, W=W, precision=precision,
            count_proxy=count_proxy, packed4=packed4, any_cat=any_cat,
            bins_bytes=bins_bytes,
            n_meas=_hist_measure_rows(cands, F, bins_bytes))
        choice = t.best("fused_hist_gpu" if fused else "wave_hist_gpu",
                        key, cands, measure,
                        default={"chunk": DEFAULT_GPU_HIST_CHUNK})
        return int(choice["chunk"])
    cands = hist_chunk_candidates(
        F=F, B=B, W=W, fused=fused, bins_bytes=bins_bytes, int8=int8,
        count_proxy=count_proxy, packed4=packed4, n_rows=n_rows,
        exhaustive=t.mode == "exhaustive", variant=variant)
    if not cands:
        return default
    if len(cands) == 1:
        return int(cands[0]["chunk"])
    tier = precision + ("+proxy" if count_proxy else "") \
        + ("+packed4" if packed4 else "") \
        + (f"+{variant}" if variant not in (None, "hilo5") else "")
    key = {"F": F, "B": B, "W": W, "tier": tier, "fused": fused,
           "cat": bool(any_cat), "bins_bytes": bins_bytes,
           "device": device_kind(),
           # the candidate set varies with n_rows (dataset-size cap +
           # int8 overflow guard): folding it into the key keeps
           # different-sized datasets from overwriting each other's
           # entries on every alternation
           "chunks": [c["chunk"] for c in cands]}
    measure = _hist_measure_fn(
        fused=fused, F=F, B=B, W=W, precision=precision,
        count_proxy=count_proxy, packed4=packed4, any_cat=any_cat,
        bins_bytes=bins_bytes,
        n_meas=_hist_measure_rows(cands, F, bins_bytes),
        variant=variant or "hilo5")
    choice = t.best("fused_hist" if fused else "wave_hist", key, cands,
                    measure, default={"chunk": default})
    return int(choice["chunk"])


# ---------------------------------------------------------------------------
# Exact-tier (precision="highest") channel-layout selection
# ---------------------------------------------------------------------------

# wave-width cap each exact-tier layout buys (128 MXU lanes / channel
# count, floor'd to a multiple of 8 for sublane alignment — see
# ops/hist_wave.py _wave_hist_kernel): the cap is what a variant is FOR
# (fewer full-data passes per tree), so it doubles as the off-TPU
# analytic preference order
EXACT_TIER_CAPS = {"hilo5": 24, "hilo4": 32, "hilo3": 40}


def exact_tier_candidates(*, constant_hessian: bool) -> List[dict]:
    """Feasible exact-tier layouts, widest wave first. ``hilo3`` (the
    fused hess/count plane) is only sound when the hessian plane is
    identically the sample mask — constant-unit-hessian objectives
    without row weights (models/gbdt.py gates this)."""
    out = [{"variant": "hilo4"}, {"variant": "hilo5"}]
    if constant_hessian:
        out.insert(0, {"variant": "hilo3"})
    return out


def tune_exact_tier(*, F: int, B: int, n_rows: int = 0,
                    constant_hessian: bool = False,
                    any_cat: bool = False, bins_bytes: int = 1,
                    requested: str = "", _measure=None) -> str:
    """The exact-semantics (hi/lo) histogram layout this geometry
    should run — "hilo5" / "hilo4" / "hilo3" (ops/hist_wave.py).

    ``requested`` is config.tpu_exact_tier ("" = auto). The choice is
    per (F, B, device) like tune_hist_chunk: on a real TPU the
    feasible layouts are timed once (fused kernel at each layout's own
    wave cap, wall NORMALIZED PER SPLIT — t/W — because the layouts
    trade MXU dots per pass against passes per tree) and the winner is
    cached; off-TPU the choice is ANALYTIC — the CPU XLA oracle is
    layout-free, and the GPU scatter kernels accumulate one full-f32
    channel per plane (no 128-lane budget to split), so on both the
    variant only sets the wave-width cap and the widest feasible wave
    wins (fewer full-data scatter passes per tree — the measured
    off-TPU win). tpu_autotune=off pins the pre-variant "hilo5".
    ``_measure`` injects a fake timer (unit tests; it forces the timed
    arm on any backend — the key's device field keeps entries
    apart)."""
    if requested:
        if requested == "hilo3" and not constant_hessian:
            log.warning(
                "tpu_exact_tier=hilo3 needs a constant-unit-hessian "
                "objective without row weights (the fused hess/count "
                "plane would misread varying hessians); using hilo4")
            return "hilo4"
        return requested
    cands = exact_tier_candidates(constant_hessian=constant_hessian)
    t = tuner()
    if t.mode == "off":
        return "hilo5"
    from ..utils.device import on_tpu
    if not on_tpu() and _measure is None:
        # the analytic arm — CPU and GPU alike (see docstring)
        return cands[0]["variant"]
    key = {"F": F, "B": B, "cat": bool(any_cat),
           "bins_bytes": bins_bytes, "device": device_kind(),
           "variants": [c["variant"] for c in cands]}
    measure = _measure or _exact_tier_measure_fn(
        F=F, B=B, any_cat=any_cat, bins_bytes=bins_bytes,
        n_rows=n_rows)
    choice = t.best("exact_tier", key, cands, measure,
                    default={"variant": "hilo5"})
    return str(choice["variant"])


def _exact_tier_measure_fn(*, F, B, any_cat, bins_bytes, n_rows):
    """measure(candidate) for the exact-tier layouts: the fused kernel
    at the candidate's own wave cap, per-split-normalized (wall / W) —
    a layout that spends 1.5x the MXU per pass but buys 1.33x the wave
    width must win or lose on the quotient, not the raw wall."""
    def measure(cand):
        v = cand["variant"]
        W = EXACT_TIER_CAPS[v]
        chunk_c = [{"chunk": DEFAULT_HIST_CHUNK}]
        fn = _hist_measure_fn(
            fused=True, F=F, B=B, W=W, precision="highest",
            count_proxy=False, packed4=False, any_cat=any_cat,
            bins_bytes=bins_bytes,
            n_meas=_hist_measure_rows(chunk_c, F, bins_bytes),
            variant=v)
        return fn(chunk_c[0]) / W
    return measure


# ---------------------------------------------------------------------------
# Histogram-tier selection (dense one-hot pass vs sparse scatter)
# ---------------------------------------------------------------------------

# auto-tier density ceiling: the sparse scatter touches ~nnz * W slot
# compares + 3 scatters per channel where the dense pass touches N * F
# one-hot work regardless of density — below ~1/8 density the sparse
# side wins with margin on every backend measured; the cost model is a
# rule (not a timed sweep) because the tier also changes EXACTNESS
# (see tune_hist_tier), so auto only engages where it is bit-equal
SPARSE_TIER_MAX_DENSITY = 0.125
# the GPU arm's lower ceiling: on the gpu route, choosing the sparse
# tier forfeits the pallas-gpu fused kernel (the sparse tier runs the
# XLA scatter path), so the sparse side must win by more than it does
# on backends where both tiers are XLA
SPARSE_TIER_MAX_DENSITY_GPU = 1.0 / 16.0


def tune_hist_tier(*, requested: int, density: float, nnz: int,
                   F: int, B: int, W: int, quant: bool,
                   backend: Optional[str] = None) -> bool:
    """True = the sparse histogram tier (ops/hist_wave.py
    wave_histogram_sparse, scatter over nnz) serves this booster;
    False = the dense one-hot tier. Selected per (density, geometry)
    like the other kernel tiers — the caller (models/gbdt.py) has
    already checked the structural gates (serial learner, no EFB
    bundles, coordinates present).

    ``requested`` is config.tpu_sparse (-1 auto / 0 off / 1 force).
    The auto rule is exactness-first: integer (quantized) accumulation
    is order-free, so the sparse completion subtraction is BIT-equal
    to the dense tier — auto therefore requires ``quant`` AND density
    under the backend's ceiling (SPARSE_TIER_MAX_DENSITY, or the lower
    SPARSE_TIER_MAX_DENSITY_GPU on the gpu route — ``backend`` pins it
    for decision unit tests, None reads the live backend_kind()).
    tpu_sparse=1 forces the tier for f32 histograms too (final-ulp
    reassociation drift vs the dense tier is possible; logged)."""
    if requested == 0:
        return False
    if requested == 1:
        if not quant:
            log.info("tpu_sparse=1 with f32 histograms: the sparse "
                     "tier's default-bin completion reassociates "
                     "sums — final-ulp drift vs the dense tier is "
                     "possible (tpu_quantized_hist makes it bit-exact)")
        return True
    if not quant:
        return False
    if backend is None:
        from ..utils.device import backend_kind
        backend = backend_kind()
    ceiling = (SPARSE_TIER_MAX_DENSITY_GPU if backend == "gpu"
               else SPARSE_TIER_MAX_DENSITY)
    return float(density) <= ceiling


# ---------------------------------------------------------------------------
# Histogram-psum wire-format tuning (data-parallel reduction)
# ---------------------------------------------------------------------------

def tune_hist_psum(*, mesh, W: int, F: int, B: int, channels: int,
                   n_rows_global: int, requested: int = -1) -> bool:
    """Wire format of the data-parallel wave-histogram reduction:
    True = psum the RAW int32 quantized histogram and dequantize after
    the collective (exact integer addition across shards, and — with
    the count-proxy tier — a 2-channel payload instead of 3);
    False = psum dequantized f32 sums (the pre-quantized-psum wire).

    ``requested`` is config.tpu_quantized_psum (-1 auto / 0 off /
    1 force). The int32 wire is only sound while the GLOBAL padded row
    count keeps 127 * n under int32 wrap — beyond that the f32 wire is
    used regardless (f32 rounds but never wraps). Inside the bound the
    auto choice is timed once per (mesh size, payload shape, device)
    key on real TPU meshes and cached; off-TPU (and with
    tpu_autotune=off) the analytic default — int32 — is used."""
    if requested == 0:
        return False
    from ..utils.device import on_tpu
    tpu = on_tpu()
    # off-TPU the "quantized wire" is the XLA oracle's integer-VALUED
    # f32 sums (hist_wave.wave_histogram), which stay exact only below
    # 2^24 — the int32 Pallas wire holds to 2^31. Past the applicable
    # bound the deferred-dequant reduction could round/wrap, so the
    # dequantize-first f32 wire (rounds, never wraps) is used instead.
    bound = 2 ** 31 if tpu else 2 ** 24
    safe = 127 * max(int(n_rows_global), 1) < bound
    if not safe:
        if requested == 1:
            log.warning("tpu_quantized_psum=1 requested but %d global "
                        "rows could overflow the quantized wire; using "
                        "the f32 reduction", n_rows_global)
        return False
    if requested == 1:
        return True
    t = tuner()
    if t.mode == "off" or not tpu:
        return True
    D = int(mesh.devices.size)
    key = {"D": D, "W": W, "F": F, "B": B, "C": channels,
           "device": device_kind()}
    cands = [{"wire": "int32"}, {"wire": "f32"}]
    choice = t.best("hist_psum", key, cands,
                    _psum_measure_fn(mesh, (W, F, B, channels)),
                    default={"wire": "int32"})
    return choice["wire"] == "int32"


def _psum_measure_fn(mesh, shape):
    """measure(candidate) for the histogram-reduction wire formats: a
    jitted shard_map psumming a dummy payload of the real [W, F, B, C]
    block in the candidate's dtype."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    # lazy: parallel.learners imports ops.wave_grower which imports
    # this module at top level
    from ..parallel.learners import AXIS, _shard_map

    def build(dtype):
        def body(x):
            return jax.lax.psum(x, AXIS)
        # jit-capture: ok(*) — throwaway psum microbenchmark body,
        # closes over nothing but the mesh axis; never cached
        f = jax.jit(_shard_map(body, mesh=mesh, in_specs=(P(),),
                               out_specs=P(), check_vma=False))
        x = jnp.ones(shape, dtype)
        return functools.partial(f, x)

    fns = {"int32": build(jnp.int32), "f32": build(jnp.float32)}
    return lambda cand: timing.measure(fns[cand["wire"]])


# packed-wire wrap bounds: the quantized per-shard histogram entry is a
# sum of int8 values in [-127, 127], so |entry| <= 127 * n_rows_global
# and the GLOBAL psum result obeys the same bound — when it fits the
# narrow signed range, the narrowing cast, the integer psum and the
# widening cast are all exact (BIT-identical to the int32 wire). The
# int32 bound itself is tune_hist_psum's concern (it gates quant_psum).
PSUM_WIRE_BOUNDS = (("int8", 2 ** 7), ("int16", 2 ** 15))


def tune_psum_wire(*, n_rows_global: int, requested: int = -1) -> str:
    """Wire dtype of the quantized histogram collective
    (config.tpu_psum_wire): "int8"/"int16" when the 127*N wrap bound
    proves the narrow sum cannot overflow, else "int32" (the legacy
    wire). ``requested``: 0 = legacy int32; 1 = force-narrow (warns
    and falls back to int32 where the bound refuses); -1 = auto
    (narrowest provably-safe width — a pure bound check, no timing:
    narrower is never slower and always bit-identical)."""
    if requested == 0:
        return "int32"
    n = max(int(n_rows_global), 1)
    for wire, bound in PSUM_WIRE_BOUNDS:
        if 127 * n < bound:
            return wire
    if requested == 1:
        log.warning("tpu_psum_wire=1 requested but %d global rows "
                    "exceed every narrow wrap bound (127*N < 2^15 "
                    "needed for int16); using the int32 wire", n)
    return "int32"


def tune_hist_psum_async(*, mesh, W: int, F: int, B: int,
                         channels: int, wire: str = "f32",
                         requested: int = -1) -> int:
    """Slot count of the wave-histogram collective
    (config.tpu_async_psum): 1 = one monolithic psum (sync);
    2 = double-buffered slot collectives split along the feature axis
    (parallel/learners.py make_hist_reduce), which XLA can overlap
    with local compute. The split is BIT-identical for every wire
    (psum is elementwise across shards), so the choice is purely a
    scheduling/perf arm: -1 = auto (slots on multi-device meshes; the
    async-vs-sync arm is timed once per (mesh, payload, device) key on
    real TPUs, analytic default — async — elsewhere); 0 = sync;
    1 = force async."""
    if requested == 0:
        return 1
    if F < 2:
        # nothing to split; the monolithic psum IS the slot psum
        if requested == 1:
            log.info("tpu_async_psum=1 with a single feature column: "
                     "the collective has one slot either way")
        return 1
    if requested == 1:
        return 2
    if int(mesh.devices.size) < 2:
        return 1
    from ..utils.device import on_tpu
    t = tuner()
    if t.mode == "off" or not on_tpu():
        return 2
    key = {"D": int(mesh.devices.size), "W": W, "F": F, "B": B,
           "C": channels, "wire": wire, "device": device_kind()}
    cands = [{"slots": 1}, {"slots": 2}]
    choice = t.best("hist_psum_async", key, cands,
                    _psum_slots_measure_fn(mesh, (W, F, B, channels),
                                           wire),
                    default={"slots": 2})
    return int(choice["slots"])


def _psum_slots_measure_fn(mesh, shape, wire: str):
    """measure(candidate) for the async-vs-sync arm: the real slot
    split (parallel/learners.py) over a dummy payload, per slot
    count."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..parallel.learners import _shard_map, _slot_psum

    dtype = {"int8": jnp.int8, "int16": jnp.int16,
             "int32": jnp.int32}.get(wire, jnp.float32)

    def build(slots):
        def body(x):
            return _slot_psum(x, slots)
        # jit-capture: ok(*) — throwaway psum microbenchmark body,
        # closes over nothing but the mesh axis; never cached
        f = jax.jit(_shard_map(body, mesh=mesh, in_specs=(P(),),
                               out_specs=P(), check_vma=False))
        x = jnp.ones(shape, dtype)
        return functools.partial(f, x)

    fns = {1: build(1), 2: build(2)}
    return lambda cand: timing.measure(fns[cand["slots"]])


def measure_psum_s(mesh, shape, dtype) -> float:
    """Measured seconds per histogram-collective pass on THIS mesh for
    the given payload — the stall-time estimate behind the
    ``comm/psum_stall_s`` accounting (models/gbdt.py): per-pass
    collective wall x pass count. A real measurement of the real
    collective (not a bandwidth model), but taken outside the training
    step — in-step timing would require host callbacks on the
    compiled path."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..parallel.learners import AXIS, _shard_map

    def body(x):
        return jax.lax.psum(x, AXIS)
    # jit-capture: ok(*) — throwaway psum microbenchmark body, closes
    # over nothing but the mesh axis; never cached
    f = jax.jit(_shard_map(body, mesh=mesh, in_specs=(P(),),
                           out_specs=P(), check_vma=False))
    x = jnp.ones(shape, dtype)
    return float(timing.measure(functools.partial(f, x)))


def _hist_measure_rows(cands: List[dict], F: int, bins_bytes: int) -> int:
    """Measurement row count: a multiple of every candidate chunk,
    capped so the synthetic bin matrix stays small."""
    top = max(c["chunk"] for c in cands)
    n = max(top, 65536)
    while n > top and F * n * bins_bytes > (512 << 20):
        n //= 2
    return n


def _hist_measure_fn(*, fused: bool, F: int, B: int, W: int,
                     precision: str, count_proxy: bool, packed4: bool,
                     any_cat: bool, bins_bytes: int, n_meas: int,
                     variant: str = "hilo5"):
    """Build measure(candidate) for the histogram kernels: synthetic
    data of the real (F, B, tier) shape, one warm-up call per candidate
    (compiles; the persistent compile cache makes reruns cheap), then
    median-of-k wall time with a device-sync readback."""
    import numpy as np

    import jax.numpy as jnp

    from .hist_wave import (fused_partition_histogram_pallas,
                            wave_histogram_pallas)

    rng = np.random.default_rng(0)
    int8 = precision == "int8"
    F_rows = (F + 1) // 2 if packed4 else F
    bdt = np.uint8 if bins_bytes == 1 else np.int32
    bmax = 255 if packed4 else max(B - 1, 1)
    bins = jnp.asarray(rng.integers(0, bmax + 1, (F_rows, n_meas),
                                    dtype=np.int64).astype(bdt))
    if int8:
        g = jnp.asarray(rng.integers(-127, 128, n_meas).astype(np.float32))
        h = jnp.asarray(rng.integers(0, 128, n_meas).astype(np.float32))
        gh_scale = (1.0, 1.0)
    else:
        g = jnp.asarray(rng.normal(size=n_meas).astype(np.float32))
        h = jnp.asarray(np.abs(rng.normal(size=n_meas)).astype(np.float32))
        gh_scale = None
    leaf_ids = jnp.zeros(n_meas, jnp.int32)
    if fused:
        mask = jnp.ones(n_meas, jnp.float32)
        # one active slot splitting leaf 0 at mid-bin — representative
        # work (the MXU dots are dense regardless of slot activity)
        col = np.full(W, -1, np.int32)
        tbl = np.zeros((18, W), np.int32)
        tbl[0] = col                     # TBL_PARENT
        tbl[1] = col                     # TBL_NEW
        tbl[0, 0], tbl[1, 0] = 0, 1
        tbl[3, 0] = B // 2               # TBL_BIN
        tbl[7] = B                       # TBL_NUMBIN
        tbl[8] = col                     # TBL_SMALL
        tbl[8, 0] = 1
        tbl_d = jnp.asarray(tbl)

        def run(chunk):
            return fused_partition_histogram_pallas(
                bins, g, h, mask, leaf_ids, tbl_d, num_bins=B,
                chunk=chunk, precision=precision, gh_scale=gh_scale,
                any_cat=any_cat, count_proxy=count_proxy,
                packed4=packed4, num_features=F if packed4 else None,
                variant=variant)
    else:
        wl = jnp.asarray(np.concatenate(
            [np.zeros(1, np.int32), np.full(W - 1, -1, np.int32)])
            if W > 1 else np.zeros(1, np.int32))

        def run(chunk):
            return wave_histogram_pallas(
                bins, g, h, leaf_ids, wl, num_bins=B, chunk=chunk,
                precision=precision, gh_scale=gh_scale,
                count_proxy=count_proxy, packed4=packed4,
                num_features=F if packed4 else None, variant=variant)

    return lambda cand: timing.measure(
        functools.partial(run, int(cand["chunk"])))


def _hist_measure_fn_gpu(*, fused: bool, F: int, B: int, W: int,
                         precision: str, count_proxy: bool, packed4: bool,
                         any_cat: bool, bins_bytes: int, n_meas: int):
    """measure(candidate) for the GPU histogram kernels — the same
    synthetic-data harness as _hist_measure_fn, pointed at the
    Pallas-Triton kernels (non-interpret: this path only runs when a
    real GPU is the backend; unit tests inject ``_measure`` instead).
    No ``variant`` knob: the GPU scatter is layout-free, every hilo
    variant lowers to the same kernel."""
    import numpy as np

    import jax.numpy as jnp

    from .hist_wave import (fused_partition_histogram_pallas_gpu,
                            wave_histogram_pallas_gpu)

    rng = np.random.default_rng(0)
    int8 = precision == "int8"
    F_rows = (F + 1) // 2 if packed4 else F
    bdt = np.uint8 if bins_bytes == 1 else np.int32
    bmax = 255 if packed4 else max(B - 1, 1)
    bins = jnp.asarray(rng.integers(0, bmax + 1, (F_rows, n_meas),
                                    dtype=np.int64).astype(bdt))
    if int8:
        g = jnp.asarray(rng.integers(-127, 128, n_meas).astype(np.float32))
        h = jnp.asarray(rng.integers(0, 128, n_meas).astype(np.float32))
        gh_scale = (1.0, 1.0)
    else:
        g = jnp.asarray(rng.normal(size=n_meas).astype(np.float32))
        h = jnp.asarray(np.abs(rng.normal(size=n_meas)).astype(np.float32))
        gh_scale = None
    leaf_ids = jnp.zeros(n_meas, jnp.int32)
    if fused:
        mask = jnp.ones(n_meas, jnp.float32)
        col = np.full(W, -1, np.int32)
        tbl = np.zeros((18, W), np.int32)
        tbl[0] = col                     # TBL_PARENT
        tbl[1] = col                     # TBL_NEW
        tbl[0, 0], tbl[1, 0] = 0, 1
        tbl[3, 0] = B // 2               # TBL_BIN
        tbl[7] = B                       # TBL_NUMBIN
        tbl[8] = col                     # TBL_SMALL
        tbl[8, 0] = 1
        tbl_d = jnp.asarray(tbl)

        def run(chunk):
            return fused_partition_histogram_pallas_gpu(
                bins, g, h, mask, leaf_ids, tbl_d, num_bins=B,
                chunk=chunk, precision=precision, gh_scale=gh_scale,
                any_cat=any_cat, count_proxy=count_proxy,
                packed4=packed4, num_features=F if packed4 else None)
    else:
        wl = jnp.asarray(np.concatenate(
            [np.zeros(1, np.int32), np.full(W - 1, -1, np.int32)])
            if W > 1 else np.zeros(1, np.int32))

        def run(chunk):
            return wave_histogram_pallas_gpu(
                bins, g, h, leaf_ids, wl, num_bins=B, chunk=chunk,
                precision=precision, gh_scale=gh_scale,
                count_proxy=count_proxy, packed4=packed4,
                num_features=F if packed4 else None)

    return lambda cand: timing.measure(
        functools.partial(run, int(cand["chunk"])))
