"""On-device prediction over binned data.

Counterpart of the reference's score updating and tree prediction
(reference: src/boosting/score_updater.hpp:17-123, src/io/tree.h:212-266).
Scores for train/valid sets are maintained entirely on device: a tree's
splits are replayed over the binned matrix (same order and leaf numbering
as growth, so the assignment is identical to the grower's partition), then
leaf outputs are gathered into the score vector.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .partition import apply_split, member_column
from .split import FeatureMeta


@jax.jit
def replay_partition(rec, bins_t, meta: FeatureMeta):
    """Assign each row of ``bins_t`` [F, N] (feature-major) to a leaf of
    the recorded tree by replaying its splits (Tree numbering: split i's
    right child = leaf i+1 — the wave grower's new-id assignment keeps
    this invariant, ops/wave_grower.py).
    """
    meta = FeatureMeta(*[jnp.asarray(x) for x in meta])
    n = bins_t.shape[1]
    num_splits = rec.split_leaf.shape[0]
    leaf_ids = jnp.zeros(n, jnp.int32)

    def body(i, leaf_ids):
        feat = rec.split_feature[i]
        enabled = rec.split_leaf[i] >= 0
        safe_feat = jnp.maximum(feat, 0)
        bin_col = member_column(bins_t, safe_feat, meta)
        return apply_split(
            leaf_ids, bin_col, rec.split_leaf[i], i + 1, rec.split_bin[i],
            rec.split_default_left[i], meta.missing_type[safe_feat],
            meta.default_bin[safe_feat], meta.num_bin[safe_feat],
            enabled=enabled, is_cat=rec.split_is_cat[i],
            cat_words=rec.split_cat_words[i])

    return jax.lax.fori_loop(0, num_splits, body, leaf_ids)


@jax.jit
def add_leaf_outputs(scores, leaf_ids, leaf_output, shrinkage):
    """score += shrinkage * leaf_output[leaf] (ScoreUpdater::AddScore)."""
    return scores + shrinkage * leaf_output[leaf_ids]


def predict_trees_binned(records, bins_t, meta: FeatureMeta,
                         shrinkage_done=True):
    """Sum of leaf outputs over a list of TreeRecords for binned rows."""
    n = bins_t.shape[1]
    out = jnp.zeros(n, jnp.float32)
    for rec in records:
        leaf = replay_partition(rec, bins_t, meta)
        out = out + rec.leaf_output[leaf]
    return out
