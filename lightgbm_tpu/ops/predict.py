"""On-device prediction over binned data.

Counterpart of the reference's score updating and tree prediction
(reference: src/boosting/score_updater.hpp:17-123, src/io/tree.h:212-266).
Scores for train/valid sets are maintained entirely on device: a tree's
splits are replayed over the binned matrix (same order and leaf numbering
as growth, so the assignment is identical to the grower's partition), then
leaf outputs are gathered into the score vector.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .partition import apply_split, member_column
from .split import FeatureMeta


@jax.jit
def replay_partition(rec, bins_t, meta: FeatureMeta):
    """Assign each row of ``bins_t`` [F, N] (feature-major) to a leaf of
    the recorded tree by replaying its splits (Tree numbering: split i's
    right child = leaf i+1 — the wave grower's new-id assignment keeps
    this invariant, ops/wave_grower.py).
    """
    meta = FeatureMeta(*[jnp.asarray(x) for x in meta])
    n = bins_t.shape[1]
    num_splits = rec.split_leaf.shape[0]
    leaf_ids = jnp.zeros(n, jnp.int32)

    def body(i, leaf_ids):
        feat = rec.split_feature[i]
        enabled = rec.split_leaf[i] >= 0
        safe_feat = jnp.maximum(feat, 0)
        bin_col = member_column(bins_t, safe_feat, meta)
        return apply_split(
            leaf_ids, bin_col, rec.split_leaf[i], i + 1, rec.split_bin[i],
            rec.split_default_left[i], meta.missing_type[safe_feat],
            meta.default_bin[safe_feat], meta.num_bin[safe_feat],
            enabled=enabled, is_cat=rec.split_is_cat[i],
            cat_words=rec.split_cat_words[i])

    return jax.lax.fori_loop(0, num_splits, body, leaf_ids)


def _leaf_gather_kernel(tbl_ref, leaf_ref, out_ref, *, L):
    """out[r, c] = tbl[leaf[r, c]] (-0.0 for ids outside [0, L)).

    XLA lowers a [L]-table gather by 11M indices to a ~1.5 GB/s scalar
    loop (measured 7.7 ms per 1M rows — 14% of a whole boosting
    iteration); this kernel instead sweeps the table once with full-
    width VPU selects: L sequential compare+selects over an [8, C]
    tile, with the table in SMEM for scalar reads."""
    leaf = leaf_ref[...]                                # [8, C] i32
    def body(l, acc):
        return jnp.where(leaf == l, tbl_ref[0, l], acc)
    out_ref[...] = jax.lax.fori_loop(
        0, L, body, jnp.zeros_like(out_ref))


@functools.partial(jax.jit, static_argnames=("interpret",))
def leaf_gather_pallas(table, leaf_ids, *, interpret=False):
    """table[leaf_ids] for a small table — TPU replacement for the slow
    XLA gather. leaf_ids outside [0, len(table)) yield 0.0."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    L = table.shape[0]
    n = leaf_ids.shape[0]
    chunk = 16384                      # [8, chunk] f32 tiles in VMEM
    block = 8 * chunk
    pad = (-n) % block
    lv = jnp.pad(leaf_ids, (0, pad), constant_values=-1) \
        .reshape(8, -1)                # row-major [8, n_pad/8]
    tbl = table.astype(jnp.float32)[None, :]            # [1, L]
    out = pl.pallas_call(
        functools.partial(_leaf_gather_kernel, L=L),
        grid=(lv.shape[1] // chunk,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((8, chunk), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((8, chunk), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(lv.shape, jnp.float32),
        interpret=interpret,
    )(tbl, lv)
    return out.reshape(-1)[:n]


def leaf_gather(table, leaf_ids):
    """Dispatch: Pallas sweep on TPU, plain XLA gather elsewhere."""
    from ..utils.device import on_tpu
    if on_tpu() and table.shape[0] <= 4096 and leaf_ids.shape[0] >= 8:
        return leaf_gather_pallas(table, leaf_ids)
    return table[leaf_ids]


@jax.jit
def add_leaf_outputs(scores, leaf_ids, leaf_output, shrinkage):
    """score += shrinkage * leaf_output[leaf] (ScoreUpdater::AddScore)."""
    return scores + shrinkage * leaf_gather(leaf_output, leaf_ids)


def predict_trees_binned(records, bins_t, meta: FeatureMeta,
                         shrinkage_done=True):
    """Sum of leaf outputs over a list of TreeRecords for binned rows."""
    n = bins_t.shape[1]
    out = jnp.zeros(n, jnp.float32)
    for rec in records:
        leaf = replay_partition(rec, bins_t, meta)
        out = out + leaf_gather(rec.leaf_output, leaf)
    return out
