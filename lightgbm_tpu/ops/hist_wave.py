"""Wave histogram construction: W leaves' histograms in one data pass.

TPU-native replacement for the reference's per-leaf histogram kernels
(reference: src/io/dense_bin.hpp:72-130 CPU loops,
src/treelearner/ocl/histogram256.cl:345 OpenCL device kernels). Two key
departures from round 1's per-leaf one-hot einsum:

1. **Wave batching.** The MXU matmul that accumulates histograms has
   128 output lanes but a single leaf only needs 3 channels
   (grad, hess, count). Filling the idle lanes with OTHER leaves'
   channels makes one full-data pass produce histograms for up to
   ``W = 128 // 3 = 42`` leaves at the price of one — the per-wave
   analog of the OpenCL kernel's one-workgroup-per-feature-group
   batching.

2. **No materialized one-hot.** Round 1's ``jax.nn.one_hot`` einsum
   wrote a [N, F, B] float tensor through HBM (7 GB per pass at the
   HIGGS size — the measured 5.5 ms/pass was pure HBM traffic). The
   Pallas kernel builds the one-hot tiles in VMEM and feeds the MXU
   directly.

Data layout is **feature-major**: ``bins_t [F, N]`` so that a feature's
bin row is a contiguous lane vector — the transposed one-hot tile
``[group*B, Ct]`` is then built by broadcast compares with no VMEM
relayout, and the accumulating matmul ``oh_t @ w`` is in canonical
[M, K] x [K, N] form for the MXU.

Output layout: ``[W, F, B, 3]`` with channel 0=sum_grad, 1=sum_hess,
2=count, matching round 1's per-leaf ``[F, B, 3]``.

The XLA implementation is the fallback (CPU tests, any-backend
correctness oracle); the Pallas kernel is used on TPU.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import autotune


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _feature_row(bins_ref, f: int, cache: dict, packed4: bool):
    """Logical feature ``f``'s bin row as i32 lanes (shared by the wave
    and fused kernels). 4-bit tier: two features per byte row (feature
    2p in the low nibble of row p); each byte row is widened once per
    kernel invocation via ``cache``."""
    if not packed4:
        return bins_ref[f, :].astype(jnp.int32)
    pr = f // 2
    if pr not in cache:
        cache[pr] = bins_ref[pr, :].astype(jnp.int32)
    r = cache[pr]
    return (jax.lax.shift_right_logical(r, 4) if f % 2
            else jnp.bitwise_and(r, 15))


def _bf16_split(x):
    """Split f32 into (hi, lo) with hi exactly bf16-representable and
    hi + lo == x exactly. Bit-truncation of the low 16 mantissa bits —
    NOT astype(bf16).astype(f32) (XLA's simplifier elides that convert
    round-trip as identity, silently zeroing lo) and NOT
    lax.reduce_precision (unimplemented in Pallas TPU lowering).
    Truncation instead of round-to-nearest is fine: the decomposition
    only needs hi to be exact under the MXU's bf16 input rounding."""
    xi = jax.lax.bitcast_convert_type(x, jnp.int32)
    hi = jax.lax.bitcast_convert_type(xi & jnp.int32(-65536), jnp.float32)
    return hi, x - hi


# ---------------------------------------------------------------------------
# XLA reference implementation
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("num_bins", "chunk",
                                             "precision"))
def wave_histogram_xla(bins_t, g, h, leaf_ids, wave_leaves, *, num_bins,
                       chunk=0, precision="highest"):
    """[W, F, B, 3] histograms of the rows of each wave leaf.

    Scatter-add formulation: each (row, feature) contributes its
    (g, h, 1) to flat index ``slot*F*B + f*B + bin``. This is the
    CPU/any-backend correctness oracle — XLA lowers the scatter to a
    sequential loop, which is fast on CPU and exactly associative; the
    MXU one-hot design lives in the Pallas kernel below. (The previous
    oracle materialized the [F, N, B] one-hot through memory — hundreds
    of MB per pass.) ``chunk``/``precision`` are accepted for interface
    parity with the Pallas path; the scatter needs neither.

    Args:
      bins_t:      [F, N] integer bin matrix, feature-major (uint8/int32).
      g, h:        [N] f32 gradient/hessian (bagging mask already folded:
                   masked-out rows carry g = h = 0 and count rides on
                   leaf membership, so set their leaf_ids to -1).
      leaf_ids:    [N] int32 current leaf assignment (-1 = out of bag).
      wave_leaves: [W] int32 leaf ids whose histograms are wanted
                   (-1 slots produce a zero histogram).
    """
    F, n = bins_t.shape
    W = wave_leaves.shape[0]
    B = num_bins
    eq = (leaf_ids[None, :] == wave_leaves[:, None]) \
        & (wave_leaves >= 0)[:, None]                     # [W, N]
    found = eq.any(axis=0)
    slot = jnp.argmax(eq, axis=0).astype(jnp.int32)       # [N]
    base = jnp.where(found, slot * (F * B), W * F * B)    # OOB -> dropped
    return _scatter_hist3(bins_t, g, h, base, num_bins=B, num_slots=W)


def _scatter_hist3(bins_t, g, h, base, *, num_bins, num_slots):
    """ONE combined scatter-add of all three channels: per (row,
    feature) the flat target is ``base_row + f*B + bin`` and the
    update is the 3-vector (g, h, 1). One pass over the F*N indices
    instead of three — measured 1.5x on the CPU backend at the bench
    shape — and BIT-identical to three per-channel scatters (each
    target's per-channel add sequence is the same row order either
    way). ``base`` carries each row's wave-slot offset, with
    out-of-wave rows at the OOB-high sentinel ``num_slots*F*B`` that
    ``mode="drop"`` discards (negative sentinels would wrap
    python-style)."""
    F, n = bins_t.shape
    B = num_bins
    size = num_slots * F * B
    flat = (base[None, :] + jnp.arange(F, dtype=jnp.int32)[:, None] * B
            + bins_t.astype(jnp.int32)).ravel()           # [F*N]
    vals = jnp.stack([
        jnp.broadcast_to(g.astype(jnp.float32), (F, n)),
        jnp.broadcast_to(h.astype(jnp.float32), (F, n)),
        jnp.broadcast_to(jnp.ones((), jnp.float32), (F, n))],
        axis=-1).reshape(-1, 3)                           # [F*N, 3]
    hist = jnp.zeros((size, 3), jnp.float32).at[flat].add(
        vals, mode="drop")
    return hist.reshape(num_slots, F, B, 3)


# ---------------------------------------------------------------------------
# Fused partition + wave histogram, XLA formulation (the off-TPU hot path)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("num_bins", "count_proxy",
                                             "dequant"))
def fused_partition_histogram_xla(bins_t, g, h, sample_mask, leaf_ids,
                                  wl, new_ids, feat, tbin, dleft,
                                  iscat, catw, small_ids, miss, defb,
                                  nb, *, num_bins, count_proxy=False,
                                  gh_scale=None, dequant=True):
    """Partition one wave + build its smaller-child histograms in one
    traced region — the XLA twin of ``fused_partition_histogram_pallas``
    for backends without the Pallas kernels (the exact tier's off-TPU
    hot path).

    What fusing buys over [apply_wave_splits -> wave_histogram_xla]:
    the leaf-membership compare ``eq`` [W, N] is computed ONCE and
    reused for (a) the partition's move mask and (b) the smaller-child
    histogram membership (the unfused pipeline re-derives membership
    from the POST-split leaf ids — a second [W, N] compare sweep plus
    an argmax), and the three histogram channels ride one combined
    scatter (``_scatter_hist3``). BIT-identical to the unfused
    pipeline: the partition applies the same ``row_goes_right``
    decisions (rows match at most one slot, so the vectorized
    destination sum equals the sequential select chain) and the
    scatter consumes the identical flat-index sequence the oracle
    builds from the post-split leaf ids.

    Per-slot split parameters ride as [W] vectors (the Pallas kernel's
    packed table, unpacked): ``wl``/``new_ids``/``small_ids`` are the
    wave's parent/right-child/smaller-child leaf ids (-1 = inactive
    slot), ``miss``/``defb``/``nb`` the split features' missing-type /
    default-bin / bin-count metadata. g/h must be pre-masked by
    ``sample_mask``; out-of-bag rows partition but never count.

    With ``count_proxy`` also returns each slot's EXACT in-bag
    moved-row count (the right-child count, from the partition mask —
    the same synthesis the Pallas fused kernel does). ``gh_scale`` +
    ``dequant`` mirror the dispatcher's quantized-tier handling: the
    scatter is exact on integer-valued f32, and dequantization (or the
    deferred quant-psum wire) happens on the way out.
    """
    from .partition import row_goes_right

    F, n = bins_t.shape
    B = num_bins
    W = wl.shape[0]
    i32 = jnp.int32
    active = wl >= 0
    safe_feat = jnp.maximum(feat, 0)
    cols = bins_t[safe_feat].astype(i32)                  # [W, N]
    right = jax.vmap(
        lambda c, tb, dl, ms, db, nbk, ic, cw: row_goes_right(
            c, tb, dl, ms, db, nbk, is_cat=ic, cat_words=cw)
    )(cols, tbin, dleft, miss, defb, nb, iscat, catw)     # [W, N]
    eq = (leaf_ids[None, :] == wl[:, None]) & active[:, None]
    moved = eq & right
    # destination via (new_id + 1): rows match at most one slot (wave
    # leaves are distinct), so the masked sum IS the select chain
    dest1 = jnp.sum(jnp.where(moved, new_ids[:, None] + 1, 0), axis=0)
    leaf_new = jnp.where(dest1 > 0, dest1 - 1, leaf_ids).astype(i32)

    # smaller-child membership from the ALREADY-COMPUTED masks: row r
    # lands in slot k's smaller child iff it was in parent k and its
    # move direction matches the smaller side — no post-split compare
    in_bag = sample_mask > 0
    small_right = small_ids == new_ids                    # [W]
    memb = (eq & (moved == small_right[:, None])
            & (small_ids >= 0)[:, None] & in_bag[None, :])
    found = memb.any(axis=0)
    slot = jnp.argmax(memb, axis=0).astype(i32)
    base = jnp.where(found, slot * (F * B), W * F * B)
    hist = _scatter_hist3(bins_t, g, h, base, num_bins=B, num_slots=W)
    if gh_scale is not None and dequant:
        hist = hist * _qscale_vec(gh_scale)
    if not count_proxy:
        return leaf_new, hist
    cnt_r = jnp.sum((moved & in_bag[None, :]).astype(jnp.float32),
                    axis=1)
    return leaf_new, hist, cnt_r


# ---------------------------------------------------------------------------
# Sparse histogram tier (CSR-native datasets, io/sparse.py)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("num_bins",
                                             "num_features"))
def wave_histogram_sparse(sp, g, h, leaf_ids, wave_leaves, *, num_bins,
                          num_features, gh_scale=None):
    """[W, F, B, 3] wave histograms by scatter over the nnz explicit
    entries — the O(nnz) tier for CSR-native datasets.

    ``sp`` = (codes, feat, row, zero_bins): per-entry bin code, INNER
    feature index and global row of every explicit entry (device
    planes from io/ingest.py SparseDeviceBinner or host coords from
    io/dataset.py), plus the per-feature bin of the implicit value 0.0.
    Sentinel (pad) entries carry ``feat >= F`` and are dropped.

    Three scatter families per channel instead of the dense one-hot
    pass over N x F:

    - explicit entries add their row's (g, h, 1) at
      ``slot*F*B + feat*B + code``  — O(nnz);
    - per-(slot, feature) explicit subtotals at ``slot*F + feat`` and
      per-slot row totals (O(nnz + N)) complete the DEFAULT bin:
      ``hist[w, f, zero_bin_f] += leaf_total_w - explicit_subtotal_wf``
      (the implicit cells of feature f in leaf w are exactly the
      leaf's rows minus its explicit entries — the EFB module uses the
      same most-frequent-bin complement, io/efb.py).

    Exactness: with integer-valued g/h (tpu_quantized_hist) and counts,
    every sum is exact, so the result is BIT-equal to the dense
    ``wave_histogram_xla`` — order-free integers make the completion
    subtraction exact. With raw f32 gradients the completion
    reassociates the default-bin sum, so final-ulp drift vs the dense
    tier is possible (the tpu_sparse=-1 auto rule therefore requires
    quantized histograms; =1 forces the tier anyway).

    ``gh_scale`` dequantizes quantized sums exactly like the dense XLA
    path (same scalar multiply on equal integer sums -> bit-equal
    f32)."""
    codes, feat, row, zb = sp
    F = num_features
    B = num_bins
    W = wave_leaves.shape[0]
    size = W * F * B
    f32 = jnp.float32
    feat = feat.astype(jnp.int32)
    codes = codes.astype(jnp.int32)
    row = row.astype(jnp.int32)

    # entry -> wave slot via its row's leaf (mirrors the dense oracle)
    lr = leaf_ids[row]                                    # [E]
    eq = (lr[None, :] == wave_leaves[:, None]) \
        & (wave_leaves >= 0)[:, None]                     # [W, E]
    found = eq.any(axis=0) & (feat < F)
    slot = jnp.argmax(eq, axis=0).astype(jnp.int32)
    flat = jnp.where(found, slot * (F * B) + feat * B + codes, size)
    flatf = jnp.where(found, slot * F + feat, W * F)

    # row -> wave slot for the per-leaf totals
    eqr = (leaf_ids[None, :] == wave_leaves[:, None]) \
        & (wave_leaves >= 0)[:, None]                     # [W, N]
    slotr = jnp.where(eqr.any(axis=0),
                      jnp.argmax(eqr, axis=0).astype(jnp.int32), W)

    # default-bin completion targets: (w, f) -> flat bin index of f's
    # zero bin in slot w
    didx = (jnp.arange(W, dtype=jnp.int32)[:, None] * (F * B)
            + jnp.arange(F, dtype=jnp.int32)[None, :] * B
            + zb.astype(jnp.int32)[None, :]).reshape(-1)  # [W*F]

    def chan(v):
        ev = v[row].astype(f32)
        he = jnp.zeros(size, f32).at[flat].add(ev, mode="drop")
        sub = jnp.zeros(W * F, f32).at[flatf].add(ev, mode="drop")
        ls = jnp.zeros(W + 1, f32).at[slotr].add(v.astype(f32))[:W]
        return he.at[didx].add((ls[:, None] - sub.reshape(W, F))
                               .reshape(-1))

    hist = jnp.stack([chan(g), chan(h),
                      chan(jnp.ones_like(g, f32))], axis=1)
    hist = hist.reshape(W, F, B, 3)
    if gh_scale is not None:
        hist = hist * _qscale_vec(gh_scale)
    return hist


# ---------------------------------------------------------------------------
# Pallas TPU kernel
# ---------------------------------------------------------------------------

def _wave_hist_kernel(wl_ref, bins_ref, ghl_ref, out_ref, *maybe_cnt,
                      F, B, W, groups, group_sz, variant,
                      exact_dot=False, int8=False, count_proxy=False,
                      packed4=False):
    """One grid step = one row chunk; accumulates into out_ref (VMEM).

    Every tensor keeps ROWS ON THE LANE AXIS — no relayouts anywhere:
    the weight matrix is built transposed ([channels, Ct] on sublanes)
    and the MXU dot contracts the lane axis of both operands.

    wl_ref:   [Wp, 1] f32 wave leaf ids as a column (-1 = inactive)
    bins_ref: [Fp, Ct] feature-major bins (uint8)
    ghl_ref:  [4, Ct] f32 packed rows (grad, hess, leaf_id, 0)
    out_ref:  [groups, gb_pad, 128] accumulated histograms
    maybe_cnt: with variant="hilo4", a second [groups, gb_pad, 128]
              accumulator carrying the exact count channels

    ``variant`` selects the exact-tier (precision="highest") channel
    layout — bf16 hi/lo decompositions make every MXU product exact,
    and hi + lo restores ~16 mantissa bits (the reference's f32
    histogram accuracy, GPU-Performance.rst) at full bf16 MXU speed:

    - "hilo5": [g_hi | g_lo | h_hi | h_lo | count] x W, 5W <= 128 ->
      W <= 25. One dot per feature group (the original layout).
    - "hilo4": [g_hi | g_lo | h_hi | h_lo] x W, 4W <= 128 -> W <= 32,
      with the exact counts accumulated by a SECOND dot of the same
      one-hot tile against the membership rows into ``maybe_cnt`` —
      more MXU work per pass, 25% fewer full-data passes per tree
      (the pass count is what an HBM-bound geometry pays for).
    - "hilo3": [g_hi | g_lo | count] x W, 3W <= 128 -> W <= 42. The
      hess plane is FUSED with the count plane — sound ONLY when the
      hessian is identically the sample mask (constant-unit-hessian
      objectives: L2/L1/quantile/Huber without row weights), where
      sum(h) == count bin-for-bin and bit-for-bit (the caller gates
      this, models/gbdt.py).

    ``variant=None`` (precision="default") keeps the single-bf16 rows
    [g | h | count] x W (3W <= 128), grad/hess rounding to bf16.
    """
    step = pl.program_id(0)
    cnt_ref = maybe_cnt[0] if variant == "hilo4" else None

    @pl.when(step == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)
        if cnt_ref is not None:
            cnt_ref[...] = jnp.zeros_like(cnt_ref)

    gvec = ghl_ref[0:1, :]                              # [1, Ct]
    hvec = ghl_ref[1:2, :]
    lvec = ghl_ref[2:3, :]
    wl = wl_ref[...]                                    # [Wp, 1]
    mw = ((lvec == wl[:W]) & (wl[:W] >= 0.0)).astype(jnp.float32)
    cnt_rows = None
    if int8 and count_proxy:
        # count-proxy: 2 channels only (see fused kernel / wave_grower)
        w_rows = jnp.concatenate([mw * gvec, mw * hvec], axis=0)
    elif int8:
        # quantized mode: gvec/hvec carry integer values in [-127, 127]
        # (tpu_quantized_hist, see wave_grower); int8 x int8 -> int32
        # MXU products are exact and run at 2x the bf16 rate
        w_rows = jnp.concatenate([mw * gvec, mw * hvec, mw], axis=0)
    elif variant == "hilo5":                            # mw: [W, Ct]
        g_hi, g_lo = _bf16_split(gvec)
        h_hi, h_lo = _bf16_split(hvec)
        w_rows = jnp.concatenate(
            [mw * g_hi, mw * g_lo, mw * h_hi, mw * h_lo, mw], axis=0)
    elif variant == "hilo4":
        g_hi, g_lo = _bf16_split(gvec)
        h_hi, h_lo = _bf16_split(hvec)
        w_rows = jnp.concatenate(
            [mw * g_hi, mw * g_lo, mw * h_hi, mw * h_lo], axis=0)
        cnt_rows = mw                                   # [W, Ct]
    elif variant == "hilo3":
        # constant-unit-hessian layout: the count plane IS the hess
        # plane (sum over a bin of h == m is exactly its row count)
        g_hi, g_lo = _bf16_split(gvec)
        w_rows = jnp.concatenate([mw * g_hi, mw * g_lo, mw], axis=0)
    else:
        w_rows = jnp.concatenate([mw * gvec, mw * hvec, mw], axis=0)
    nrow = w_rows.shape[0]
    if nrow != 128:
        w_rows = jnp.pad(w_rows, ((0, 128 - nrow), (0, 0)))
    if cnt_rows is not None and cnt_rows.shape[0] != 128:
        cnt_rows = jnp.pad(cnt_rows,
                           ((0, 128 - cnt_rows.shape[0]), (0, 0)))

    ct = gvec.shape[1]
    Bp = _round_up(B, 8)       # 8-aligned per-feature stride: the
    gb = group_sz * Bp         # concat below must not shuffle sublanes
    bin_iota = jax.lax.broadcasted_iota(jnp.int32, (Bp, 1), 0)
    # bf16 operands halve the one-hot tiles' footprint; numerically
    # identical to the DEFAULT bf16 MXU pass (interpret mode keeps f32
    # for the HIGHEST-precision CPU oracle)
    if int8:
        oh_dt = jnp.int8
        w_mm = w_rows.astype(jnp.int8)
        acc_dt = jnp.int32
    else:
        oh_dt = jnp.float32 if exact_dot else jnp.bfloat16
        w_mm = w_rows if exact_dot else w_rows.astype(jnp.bfloat16)
        acc_dt = jnp.float32

    rows_cache = {}
    for p in range(groups):
        # per-feature one-hot blocks concatenated on ALIGNED sublane
        # boundaries: one compare per feature (the previous
        # which_feat/select merge was VPU-bound — 2 selects + compare
        # per element vs 1 compare here)
        blocks = []
        for sidx in range(group_sz):
            f = p * group_sz + sidx
            if f < F:
                row = _feature_row(bins_ref, f, rows_cache, packed4)
                blocks.append(
                    (row[None, :] == bin_iota).astype(oh_dt))
            else:
                blocks.append(jnp.zeros((Bp, ct), oh_dt))
        oh_t = (blocks[0] if group_sz == 1
                else jnp.concatenate(blocks, axis=0))   # [gb, Ct]
        # contract the LANE axis of both operands: [gb, Ct] x [128, Ct]
        # -> [gb, 128]. DEFAULT precision = one bf16 MXU pass; one-hot
        # entries and the hi/lo rows are exactly bf16-representable, so
        # the pass is exact and hi + lo restores f32-grade sums. In
        # interpret mode (CPU tests) the XLA CPU "default" dot has
        # different split-precision numerics, so force HIGHEST there.
        acc = jax.lax.dot_general(
            oh_t, w_mm, dimension_numbers=(((1,), (1,)), ((), ())),
            precision=(None if int8
                       else jax.lax.Precision.HIGHEST if exact_dot
                       else jax.lax.Precision.DEFAULT),
            preferred_element_type=acc_dt)              # [gb, 128]
        gb_pad = out_ref.shape[1]
        if gb_pad != gb:
            acc = jnp.pad(acc, ((0, gb_pad - gb), (0, 0)))
        out_ref[p, :, :] += acc
        if cnt_rows is not None:
            # hilo4: the count channels ride a SECOND dot of the SAME
            # one-hot tile against the membership rows (0/1 products
            # are exact in bf16; integer sums < 2^24 are exact in f32)
            cnt_mm = (cnt_rows if exact_dot
                      else cnt_rows.astype(jnp.bfloat16))
            acc_c = jax.lax.dot_general(
                oh_t, cnt_mm, dimension_numbers=(((1,), (1,)), ((), ())),
                precision=(jax.lax.Precision.HIGHEST if exact_dot
                           else jax.lax.Precision.DEFAULT),
                preferred_element_type=jnp.float32)
            if gb_pad != gb:
                acc_c = jnp.pad(acc_c, ((0, gb_pad - gb), (0, 0)))
            cnt_ref[p, :, :] += acc_c


def _exact_nchan(variant) -> int:
    """MXU weight-row channels per wave slot of an exact-tier
    (precision="highest") layout — the lane-budget denominator
    (128 // nchan = the wave-width cap the variant buys)."""
    return {"hilo5": 5, "hilo4": 4, "hilo3": 3}[variant]


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "chunk", "interpret",
                                    "precision", "count_proxy",
                                    "packed4", "num_features",
                                    "dequant", "variant"))
def wave_histogram_pallas(bins_t, g, h, leaf_ids, wave_leaves, *, num_bins,
                          chunk=2048, interpret=False, precision="highest",
                          gh_scale=None, count_proxy=False,
                          packed4=False, num_features=None,
                          dequant=True, variant="hilo5"):
    """Pallas wave histogram — same contract as wave_histogram_xla.

    Grid over row chunks; per chunk the kernel builds the leaf-membership
    weight matrix and the transposed per-feature-group one-hot tiles in
    VMEM and accumulates ``one_hot_t @ w`` MXU products into a
    VMEM-resident accumulator (the per-workgroup partial-histogram design
    of ocl/histogram256.cl:345, with the partial-sum reduction done by
    grid revisiting instead of atomics).

    precision="highest" uses the bf16 hi/lo weight decomposition (exact
    products, ~f32-sum accuracy, needs wave W <= 25); "default" uses
    single bf16 weights (W <= 42, grad/hess round to bf16);
    "int8" expects PRE-QUANTIZED integer-valued g/h in [-127, 127]
    (tpu_quantized_hist) and accumulates exactly in int32 at 2x MXU
    rate (W <= 42) — ``gh_scale`` = (g_scale, h_scale) dequantizes the
    output back to f32 sums. ``dequant=False`` defers that scaling and
    returns the RAW int32 sums instead (the quantized-psum wire format:
    the data-parallel learner reduces the integer representation across
    the mesh and dequantizes after the collective, ops/wave_grower.py).
    """
    F, n = bins_t.shape
    if packed4:
        if num_bins > 16:
            raise NotImplementedError("packed4 needs max_bin <= 16")
        if not (count_proxy or precision == "highest"):
            raise NotImplementedError(
                "packed4 needs the count-proxy or hi/lo exact tier")
        F = int(num_features)
    W = int(wave_leaves.shape[0])
    B = num_bins
    int8 = precision == "int8"
    if count_proxy and not int8:
        raise NotImplementedError("count_proxy requires precision='int8'")
    hilo = precision == "highest"
    variant = variant if hilo else None
    nchan = ((2 if count_proxy else 3) if int8
             else _exact_nchan(variant) if hilo else 3)
    ncol = nchan * W
    if ncol > 128:
        raise NotImplementedError(
            f"wave_size {W} needs {nchan}W <= 128 lanes")
    if int8 and 127 * (n + (-n) % chunk) >= 2 ** 31:
        raise NotImplementedError(
            "int8 histogram sums could overflow int32 beyond ~16.9M "
            "rows; disable tpu_quantized_hist")
    # tile geometry + block shapes from the shared source of truth the
    # autotuner's VMEM predicate prices (ops/autotune.py)
    geom = autotune.hist_geometry(F=F, B=B, W=W, F_rows=bins_t.shape[0])
    group_sz, gb = geom["group_sz"], geom["gb"]
    groups, gb_pad = geom["groups"], geom["gb_pad"]

    pad = (-n) % chunk
    if pad:
        bins_t = jnp.pad(bins_t, ((0, 0), (0, pad)))
        g = jnp.pad(g, (0, pad))
        h = jnp.pad(h, (0, pad))
        leaf_ids = jnp.pad(leaf_ids, (0, pad), constant_values=-1)
    n_pad = n + pad

    ghl = jnp.stack([
        g.astype(jnp.float32), h.astype(jnp.float32),
        leaf_ids.astype(jnp.float32), jnp.zeros_like(g, jnp.float32)],
        axis=0)                                          # [4, N]
    wp = geom["wp"]
    wl = wave_leaves.astype(jnp.float32)[:, None]        # [W, 1]
    if wp != W:
        wl = jnp.pad(wl, ((0, wp - W), (0, 0)), constant_values=-1.0)

    kernel = functools.partial(
        _wave_hist_kernel, F=F, B=B, W=W, groups=groups,
        group_sz=group_sz, variant=variant,
        exact_dot=interpret and not int8,
        int8=int8, count_proxy=count_proxy, packed4=packed4)

    blk = autotune.wave_hist_block_shapes(chunk=chunk, geom=geom)
    out_specs = [pl.BlockSpec(blk["hist"], lambda i: (0, 0, 0),
                              memory_space=pltpu.VMEM)]
    out_shape = [jax.ShapeDtypeStruct(
        blk["hist"], jnp.int32 if int8 else jnp.float32)]
    if variant == "hilo4":
        # second accumulator: the count-dot channels (f32, W lanes)
        out_specs.append(pl.BlockSpec(blk["hist"], lambda i: (0, 0, 0),
                                      memory_space=pltpu.VMEM))
        out_shape.append(jax.ShapeDtypeStruct(blk["hist"], jnp.float32))
    outs = pl.pallas_call(
        kernel,
        grid=(n_pad // chunk,),
        in_specs=[
            pl.BlockSpec(blk["wl"], lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(blk["bins"], lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(blk["ghl"], lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(out_specs[0] if len(out_specs) == 1
                   else tuple(out_specs)),
        out_shape=(out_shape[0] if len(out_shape) == 1
                   else tuple(out_shape)),
        # the unrolled group loop's temporaries exceed the 16 MB default
        # scoped-vmem cap; v5e has 128 MB physical VMEM
        compiler_params=autotune.tpu_compiler_params(),
        interpret=interpret,
    )(wl, bins_t, ghl)
    out = outs[0] if variant == "hilo4" else outs

    # [groups, gb_pad, 128] -> [F, B, ncol] -> [W, F, B, 3]
    # (feature rows sit at the aligned Bp stride; slice back to B)
    out = out[:, :gb, :ncol].reshape(
        groups * group_sz, geom["Bp"], ncol)[:F, :B]
    if variant == "hilo5":
        out = out.reshape(F, B, 5, W)
        out = jnp.stack([out[:, :, 0] + out[:, :, 1],     # g = hi + lo
                         out[:, :, 2] + out[:, :, 3],     # h = hi + lo
                         out[:, :, 4]], axis=2)           # count
        return out.transpose(3, 0, 1, 2)
    if variant == "hilo4":
        cnt = outs[1][:, :gb, :W].reshape(
            groups * group_sz, geom["Bp"], W)[:F, :B]     # [F, B, W]
        out = out.reshape(F, B, 4, W)
        out = jnp.stack([out[:, :, 0] + out[:, :, 1],     # g = hi + lo
                         out[:, :, 2] + out[:, :, 3],     # h = hi + lo
                         cnt], axis=2)                    # count (dot 2)
        return out.transpose(3, 0, 1, 2)
    if variant == "hilo3":
        out = out.reshape(F, B, 3, W)
        # the fused hess/count plane serves both output channels:
        # h == sample mask (constant-unit-hessian gate), so the bin's
        # hess sum IS its count
        return jnp.stack([out[:, :, 0] + out[:, :, 1],    # g = hi + lo
                          out[:, :, 2],                   # h = count
                          out[:, :, 2]], axis=2).transpose(3, 0, 1, 2)
    if count_proxy:
        out = out.reshape(F, B, 2, W).transpose(3, 0, 1, 2)
        if not dequant:
            return out
        return out.astype(jnp.float32) * jnp.stack(
            [jnp.float32(gh_scale[0]), jnp.float32(gh_scale[1])])
    out = out.reshape(F, B, 3, W).transpose(3, 0, 1, 2)
    if int8:
        if not dequant:
            return out
        out = out.astype(jnp.float32) * _qscale_vec(gh_scale)
    return out


def _qscale_vec(gh_scale):
    """[3] channel dequantization vector (g_scale, h_scale, 1)."""
    sg, sh = gh_scale
    return jnp.stack([jnp.float32(sg), jnp.float32(sh),
                      jnp.float32(1.0)])


def wave_histogram(bins_t, g, h, leaf_ids, wave_leaves, *, num_bins,
                   chunk=0, use_pallas=None, precision="highest",
                   gh_scale=None, count_proxy=False, dequant=True,
                   variant="hilo5", route=""):
    """Dispatch: Pallas on TPU/GPU, XLA elsewhere (force via use_pallas
    or pin an explicit ``route`` — see autotune.tune_hist_route).

    precision="int8": g/h are integer-valued (quantized) and gh_scale
    dequantizes the sums; the XLA scatter path is exact on integer
    floats as-is, so only the Pallas kernel switches dtype.
    ``dequant=False`` skips the scaling (quantized-psum wire format —
    the XLA oracle then returns integer-VALUED f32 sums, the Pallas
    kernel raw int32).
    count_proxy: the Pallas kernel returns 2 channels (g, h); the XLA
    oracle still returns 3 exact channels — proxy callers overwrite
    the count channel either way (wave_grower.bound_counts).
    variant: exact-tier channel layout (precision="highest" only; see
    _wave_hist_kernel) — the XLA oracle is layout-free, so only the
    Pallas kernel consumes it."""
    if not route:
        if use_pallas is False:
            route = "two-pass"
        else:
            route = autotune.tune_hist_route(use_pallas=use_pallas)
    if route == "pallas-gpu":
        from ..utils.device import backend_kind
        return wave_histogram_pallas_gpu(
            bins_t, g, h, leaf_ids, wave_leaves, num_bins=num_bins,
            chunk=chunk or autotune.DEFAULT_GPU_HIST_CHUNK,
            interpret=backend_kind() != "gpu",
            precision=precision, gh_scale=gh_scale,
            count_proxy=count_proxy, dequant=dequant, variant=variant)
    if route == "pallas-tpu":
        return wave_histogram_pallas(
            bins_t, g, h, leaf_ids, wave_leaves, num_bins=num_bins,
            chunk=chunk or autotune.DEFAULT_HIST_CHUNK,
            precision=precision, gh_scale=gh_scale,
            count_proxy=count_proxy, dequant=dequant, variant=variant)
    out = wave_histogram_xla(
        bins_t, g, h, leaf_ids, wave_leaves, num_bins=num_bins,
        chunk=0, precision="highest")
    if precision == "int8" and dequant:
        out = out * _qscale_vec(gh_scale)
    return out


# ---------------------------------------------------------------------------
# Fused partition + wave histogram Pallas kernel
# ---------------------------------------------------------------------------

# rows of the packed per-slot split table (int32, transposed to
# [128, TBL_ROWS] at the kernel boundary)
TBL_PARENT, TBL_NEW, TBL_FEAT, TBL_BIN, TBL_DLEFT = 0, 1, 2, 3, 4
TBL_MISS, TBL_DEFBIN, TBL_NUMBIN, TBL_SMALL, TBL_ISCAT = 5, 6, 7, 8, 9
TBL_CATW = 10           # 8 bitset words (left-set bins) follow
TBL_ROWS = 24           # padded to an int32 sublane multiple

FUSED_MAX_WAVE = 32          # 4 channels x W <= 128 MXU lanes (bf16 h)
FUSED_MAX_WAVE_HILO = 24     # 5 channels, kept a multiple of 8
FUSED_MAX_WAVE_HILO4 = 32    # 4 channels + a count dot (exact tier)
FUSED_MAX_WAVE_HILO3 = 40    # 3 channels (fused hess/count plane),
                             # 42 floor'd to a multiple of 8
FUSED_MAX_WAVE_INT8 = 42     # 3 channels (int8 gq/hq/count)
FUSED_MAX_WAVE_INT8_NC = 64  # 2 channels (count-proxy mode: the MXU dot
                             # carries only gq/hq; per-bin counts are
                             # synthesized downstream from the hessian
                             # channel and EXACT per-child counts come
                             # from the partition mask — see wave_grower)


def _fused_kernel(tbl_ref, binsf_ref, ghm_ref, leaf_ref,
                  hist_ref, leaf_out_ref, *maybe_cnt, F, B, W, groups,
                  group_sz, variant, exact_dot=False, int8=False,
                  any_cat=True, count_proxy=False, packed4=False):
    """One grid step: partition one row chunk by the wave's W splits,
    then accumulate the wave's smaller-child histograms — ONE data pass.

    Lane-natural layout throughout (rows on lanes): the partition runs
    in [W, Ct] orientation fed by feature-major bin ROWS (no row-major
    copy of the bins exists at all), per-slot split parameters are
    columns of the transposed table, and the weight matrix is built
    transposed for a lane-contracting MXU dot. No relayouts.

    tbl_ref:   [128, TBL_ROWS=24] i32 packed split table (row k = wave
               slot k, column j = TBL_* field j: 10 scalar fields then
               8 categorical left-set bitset words; parent -1 =
               inactive slot)
    binsf_ref: [F, Ct]  feature-major bins (uint8)
    ghm_ref:   [4, Ct]  f32 rows (grad, hess, bag_mask, 0); grad/hess
               pre-masked, the mask rides separately for the counts
    leaf_ref:  [1, Ct]  i32 leaf ids BEFORE this wave (all rows,
               out-of-bag included)
    hist_ref:  [groups, gb_pad, 128] accumulated histograms
    leaf_out_ref: [1, Ct] i32 leaf ids AFTER this wave

    Channel layout: the exact tier (tpu_use_dp) rides one of the
    ``variant`` layouts of _wave_hist_kernel — "hilo5"
    ([g_hi | g_lo | h_hi | h_lo | count] x W, W <= 24), "hilo4" (the
    count channel moves to a second dot into ``maybe_cnt``, W <= 32)
    or "hilo3" (the fused hess/count plane for constant-unit-hessian
    objectives, W <= 40) — all with exact bf16 products and f32-grade
    hi + lo reconstruction. ``variant=None`` (precision="default"):
    [g_hi | g_lo | h | count] x W (W <= 32), hessian single bf16
    (2^-9 relative rounding). Counts exact in every layout.
    """
    step = pl.program_id(0)
    cnt_ref = (maybe_cnt[0] if count_proxy or variant == "hilo4"
               else None)

    @pl.when(step == 0)
    def _():
        hist_ref[...] = jnp.zeros_like(hist_ref)
        if cnt_ref is not None:
            cnt_ref[...] = jnp.zeros_like(cnt_ref)

    i32 = jnp.int32
    leaf = leaf_ref[...]                                # [1, Ct]
    ct = leaf.shape[1]

    # per-slot split parameters as [W, 1] columns
    bin_c = tbl_ref[:W, TBL_BIN:TBL_BIN + 1]
    dleft_c = tbl_ref[:W, TBL_DLEFT:TBL_DLEFT + 1]
    miss_c = tbl_ref[:W, TBL_MISS:TBL_MISS + 1]
    defb_c = tbl_ref[:W, TBL_DEFBIN:TBL_DEFBIN + 1]
    nb_c = tbl_ref[:W, TBL_NUMBIN:TBL_NUMBIN + 1]
    parent_c = tbl_ref[:W, TBL_PARENT:TBL_PARENT + 1]
    new_c = tbl_ref[:W, TBL_NEW:TBL_NEW + 1]
    small_c = tbl_ref[:W, TBL_SMALL:TBL_SMALL + 1]
    iscat_c = tbl_ref[:W, TBL_ISCAT:TBL_ISCAT + 1]

    # ---- partition (DataPartition::Split, data_partition.hpp:109) ----
    # cols[k, :] = bins of slot k's split feature, fetched as ONE MXU
    # row-gather: a [W, F] one-hot over features times the bf16 bins
    # tile. Bin values <= 255 are exactly bf16-representable and each
    # output has a single nonzero product, so the gather is exact —
    # and it replaces the previous F-deep select sweep over [W, Ct]
    # (F x W VPU ops per row) with an F-contraction matmul.
    feat_c = tbl_ref[:W, TBL_FEAT:TBL_FEAT + 1]
    if packed4:
        # 4-bit tier (dense_nbits_bin.hpp analog): two features per
        # HBM byte. Gather the PACKED byte rows (values <= 255: exact
        # bf16), then select each slot's nibble by feat & 1.
        F2 = binsf_ref.shape[0]
        feat2_c = jax.lax.shift_right_logical(feat_c, 1)
        odd_c = jnp.bitwise_and(feat_c, 1)
        f_iota2 = jax.lax.broadcasted_iota(i32, (W, F2), 1)
        feat_oh = (f_iota2 == feat2_c).astype(jnp.bfloat16)
        bins_bf = binsf_ref[...].astype(i32) \
            .astype(jnp.bfloat16)                           # [F2, Ct]
        packed_cols = jax.lax.dot_general(
            feat_oh, bins_bf,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(i32)  # [W, Ct]
        cols = jnp.where(odd_c > 0,
                         jax.lax.shift_right_logical(packed_cols, 4),
                         jnp.bitwise_and(packed_cols, 15))
    elif B <= 128:
        # int8 gather: bin values <= 127 are exact int8, the one-hot
        # row-select dot runs at the MXU's 2x int8 rate and accumulates
        # exactly in int32
        f_iota = jax.lax.broadcasted_iota(i32, (W, F), 1)
        feat_oh8 = (f_iota == feat_c).astype(jnp.int8)      # [W, F]
        bins_i8 = binsf_ref[...].astype(i32) \
            .astype(jnp.int8)                               # [F, Ct]
        cols = jax.lax.dot_general(
            feat_oh8, bins_i8,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=i32)                      # [W, Ct]
    elif B <= 256:
        f_iota = jax.lax.broadcasted_iota(i32, (W, F), 1)
        feat_oh = (f_iota == feat_c).astype(jnp.bfloat16)   # [W, F]
        # (Mosaic has no u8->bf16 cast; hop through i32)
        bins_bf = binsf_ref[...].astype(i32) \
            .astype(jnp.bfloat16)                           # [F, Ct]
        cols = jax.lax.dot_general(
            feat_oh, bins_bf,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(i32)  # [W, Ct]
    else:
        # bins above 256 are not exactly bf16-representable: keep the
        # exact F-deep select sweep for the wide-bin tier
        cols = jnp.zeros((W, ct), i32)
        for f in range(F):
            cols = jnp.where(feat_c == f,
                             binsf_ref[f, :].astype(i32)[None, :], cols)
    # missing semantics match ops/partition.py row_goes_right; logical
    # form, not jnp.where-on-bools (Mosaic can't lower the i8->i1
    # truncation a boolean select produces). Per-slot SENTINEL bins
    # fold the missing-type tests into the cheap [W, 1] lane: -9 never
    # matches a real bin, so each [W, Ct] compare does double duty
    na_sent = jnp.where(miss_c == 2, nb_c - 1, -9)
    def_sent = jnp.where(miss_c == 1, defb_c, -9)
    is_missing = (cols == na_sent) | (cols == def_sent)
    gt = cols > bin_c
    ndl = dleft_c == 0
    # right = is_missing ? !default_left : col > threshold, in xor form
    # (two fewer [W, Ct] ops than the and/or expansion)
    right = gt ^ (is_missing & (gt ^ ndl))
    # categorical: the bin's bit set in the slot's left bitset -> LEFT
    # (dense_bin.hpp SplitCategorical); unseen/NaN bins go right.
    # Statically skipped when the dataset has no categorical features
    # (any_cat) — the 8-way word select + bit test is ~400 VPU ops/row.
    if any_cat:
        widx = jnp.right_shift(cols, 5)
        word = jnp.zeros_like(cols)
        for wq in range(8):
            word = jnp.where(
                widx == wq,
                tbl_ref[:W, TBL_CATW + wq:TBL_CATW + wq + 1],
                word)
        cat_left = jnp.bitwise_and(
            jnp.right_shift(word, jnp.bitwise_and(cols, 31)), 1) != 0
        # logical form (no bool select — see `right` above)
        iscat_b = iscat_c > 0
        right = (iscat_b & ~cat_left) | (~iscat_b & right)
    # inactive (parent -1) slots can only match CHUNK-PADDED tail rows
    # (leaf -1; real leaf ids are never negative); their g/h/mask are
    # zero and their leaf_out is sliced off, so no >= 0 guard is needed
    moved = (leaf == parent_c) & right                      # [W, Ct]
    # destination via (new_id + 1) so inactive slots (-1 -> 0) drop out
    # of the sum and the `any` reduce is folded into one pass
    dest1 = jnp.sum(jnp.where(moved, new_c + 1, 0), axis=0,
                    keepdims=True)                          # [1, Ct]
    leaf_new = jnp.where(dest1 > 0, dest1 - 1, leaf)        # [1, Ct]
    leaf_out_ref[...] = leaf_new

    # ---- transposed wave weight rows ----
    gvec = ghm_ref[0:1, :]
    hvec = ghm_ref[1:2, :]
    mvec = ghm_ref[2:3, :]
    # (small -1 slots likewise only match zero-weight padded tail rows)
    m = (leaf_new == small_c).astype(jnp.float32)           # [W, Ct]
    if count_proxy:
        # exact per-slot right-child counts from the partition mask:
        # the count CHANNEL is gone from the MXU dot, but the exact
        # in-bag row count of every new (right) child falls out of
        # `moved` for the cost of one [W, Ct] reduce — wave_grower
        # derives the left side as parent - right and synthesizes the
        # per-bin count estimates from the hessian channel
        mvd = moved.astype(jnp.float32) * mvec              # [W, Ct]
        s = jnp.sum(mvd, axis=1, keepdims=True)             # [W, 1]
        wp_c = cnt_ref.shape[0]
        if wp_c != W:
            s = jnp.pad(s, ((0, wp_c - W), (0, 0)))
        cnt_ref[...] += jnp.broadcast_to(s, cnt_ref.shape)
    if int8 and count_proxy:
        # 2 channels x W <= 128 lanes -> waves up to 64 leaves wide,
        # cutting full-data passes per tree (the count channel's lane
        # budget bought more wave width than the counts were worth)
        w_rows = jnp.concatenate([m * gvec, m * hvec], axis=0)  # [2W, Ct]
    elif int8:
        # quantized mode (tpu_quantized_hist): gvec/hvec hold integers
        # in [-127, 127]; int8 MXU products, exact int32 sums, 2x rate
        w_rows = jnp.concatenate(
            [m * gvec, m * hvec, m * mvec], axis=0)          # [3W, Ct]
    elif variant == "hilo5":
        g_hi, g_lo = _bf16_split(gvec)
        h_hi, h_lo = _bf16_split(hvec)
        w_rows = jnp.concatenate(
            [m * g_hi, m * g_lo, m * h_hi, m * h_lo, m * mvec],
            axis=0)                                          # [5W, Ct]
    elif variant == "hilo4":
        # count channels move to a second dot (see _wave_hist_kernel)
        g_hi, g_lo = _bf16_split(gvec)
        h_hi, h_lo = _bf16_split(hvec)
        w_rows = jnp.concatenate(
            [m * g_hi, m * g_lo, m * h_hi, m * h_lo], axis=0)  # [4W, Ct]
        cnt_rows = m * mvec
    elif variant == "hilo3":
        # fused hess/count plane (h == mask, see _wave_hist_kernel)
        g_hi, g_lo = _bf16_split(gvec)
        w_rows = jnp.concatenate(
            [m * g_hi, m * g_lo, m * mvec], axis=0)          # [3W, Ct]
    else:
        g_hi, g_lo = _bf16_split(gvec)
        w_rows = jnp.concatenate(
            [m * g_hi, m * g_lo, m * hvec, m * mvec], axis=0)  # [4W, Ct]
    if variant != "hilo4":
        cnt_rows = None
    nrow = w_rows.shape[0]
    if nrow != 128:
        w_rows = jnp.pad(w_rows, ((0, 128 - nrow), (0, 0)))
    if cnt_rows is not None and cnt_rows.shape[0] != 128:
        cnt_rows = jnp.pad(cnt_rows,
                           ((0, 128 - cnt_rows.shape[0]), (0, 0)))

    # ---- one-hot tiles + lane-contracting MXU accumulate ----
    Bp = _round_up(B, 8)       # aligned per-feature stride (see
    gb = group_sz * Bp         # _wave_hist_kernel)
    bin_iota = jax.lax.broadcasted_iota(i32, (Bp, 1), 0)
    # bf16 operands halve the one-hot tile's VMEM/register footprint;
    # numerically identical to the DEFAULT bf16 MXU pass (interpret
    # mode keeps f32 for the HIGHEST-precision CPU oracle)
    if int8:
        oh_dt = jnp.int8
        w_mm = w_rows.astype(jnp.int8)
        acc_dt = jnp.int32
    else:
        oh_dt = jnp.float32 if exact_dot else jnp.bfloat16
        w_mm = w_rows if exact_dot else w_rows.astype(jnp.bfloat16)
        acc_dt = jnp.float32
    rows_cache = {}
    for p in range(groups):
        blocks = []
        for sidx in range(group_sz):
            f = p * group_sz + sidx
            if f < F:
                row = _feature_row(binsf_ref, f, rows_cache, packed4)
                blocks.append(
                    (row[None, :] == bin_iota).astype(oh_dt))
            else:
                blocks.append(jnp.zeros((Bp, ct), oh_dt))
        oh_t = (blocks[0] if group_sz == 1
                else jnp.concatenate(blocks, axis=0))
        acc = jax.lax.dot_general(
            oh_t, w_mm, dimension_numbers=(((1,), (1,)), ((), ())),
            precision=(None if int8
                       else jax.lax.Precision.HIGHEST if exact_dot
                       else jax.lax.Precision.DEFAULT),
            preferred_element_type=acc_dt)
        gb_pad = hist_ref.shape[1]
        if gb_pad != gb:
            acc = jnp.pad(acc, ((0, gb_pad - gb), (0, 0)))
        hist_ref[p, :, :] += acc
        if cnt_rows is not None:
            # hilo4 count dot (same one-hot tile; exact 0/1 products)
            cnt_mm = (cnt_rows if exact_dot
                      else cnt_rows.astype(jnp.bfloat16))
            acc_c = jax.lax.dot_general(
                oh_t, cnt_mm,
                dimension_numbers=(((1,), (1,)), ((), ())),
                precision=(jax.lax.Precision.HIGHEST if exact_dot
                           else jax.lax.Precision.DEFAULT),
                preferred_element_type=jnp.float32)
            if gb_pad != gb:
                acc_c = jnp.pad(acc_c, ((0, gb_pad - gb), (0, 0)))
            cnt_ref[p, :, :] += acc_c


@functools.partial(jax.jit, static_argnames=("num_bins", "chunk",
                                             "interpret", "precision",
                                             "any_cat", "count_proxy",
                                             "packed4", "num_features",
                                             "dequant", "variant"))
def fused_partition_histogram_pallas(bins_t, g, h, sample_mask,
                                     leaf_ids, tbl, *, num_bins,
                                     chunk=2048, interpret=False,
                                     precision="highest",
                                     gh_scale=None, any_cat=True,
                                     count_proxy=False, packed4=False,
                                     num_features=None, dequant=True,
                                     variant="hilo5"):
    """Partition one wave + build its smaller-child histograms in ONE
    data pass. Returns (new_leaf_ids [N], hist [W, F, B, 3]) — or, with
    ``count_proxy``, (new_leaf_ids, hist [W, F, B, 2], cnt_right [W]).

    tbl: [18, W] int32 packed split table (TBL_* rows: 10 scalar
    fields + 8 categorical bitset words). g/h must be pre-masked by
    sample_mask; counts use the mask channel. Only the feature-major
    bins are read — the partition selects feature rows.

    precision="int8": g/h are pre-quantized integer-valued floats
    (tpu_quantized_hist); sums accumulate exactly in int32 at 2x MXU
    rate and ``gh_scale`` dequantizes the output. ``dequant=False``
    returns the histogram in its RAW int32 representation instead —
    the quantized-psum wire format the data-parallel learner reduces
    across the mesh before dequantizing (ops/wave_grower.py).

    count_proxy (int8 only): drop the count channel from the MXU dot
    (2 channels x W <= 128 -> waves up to 64 wide, fewer full-data
    passes per tree). The returned ``cnt_right`` holds each slot's
    EXACT in-bag row count moved to the new (right) child; per-bin
    count estimates are synthesized downstream (wave_grower).

    packed4 (count-proxy or hi/lo exact tier): ``bins_t`` is
    [ceil(F/2), N] with TWO features' 4-bit bins per byte (feature 2p
    in the low nibble of row p) — half the HBM residency for
    max_bin <= 16 datasets, like the reference's Dense4bitsBin
    (dense_nbits_bin.hpp); the kernel unpacks nibbles in VMEM. The
    nibble unpack is channel-layout-independent, so the exact hi/lo
    variants compose with it. ``num_features`` gives the logical F.
    """
    F, n = bins_t.shape
    if packed4:
        if num_bins > 16:
            raise NotImplementedError("packed4 needs max_bin <= 16")
        if not (count_proxy or precision == "highest"):
            raise NotImplementedError(
                "packed4 needs the count-proxy or hi/lo exact tier")
        F = int(num_features)
    W = int(tbl.shape[1])
    B = num_bins
    int8 = precision == "int8"
    if count_proxy and not int8:
        raise NotImplementedError("count_proxy requires precision='int8'")
    hilo = precision == "highest"
    variant = variant if hilo else None
    cap = (FUSED_MAX_WAVE_INT8_NC if int8 and count_proxy
           else FUSED_MAX_WAVE_INT8 if int8
           else {"hilo5": FUSED_MAX_WAVE_HILO,
                 "hilo4": FUSED_MAX_WAVE_HILO4,
                 "hilo3": FUSED_MAX_WAVE_HILO3}[variant] if hilo
           else FUSED_MAX_WAVE)
    if W > cap:
        raise NotImplementedError(f"fused wave needs W <= {cap}")
    if int8 and 127 * (n + (-n) % chunk) >= 2 ** 31:
        raise NotImplementedError(
            "int8 histogram sums could overflow int32 beyond ~16.9M "
            "rows; disable tpu_quantized_hist")
    nchan = ((2 if count_proxy else 3) if int8
             else _exact_nchan(variant) if hilo else 4)
    # tile geometry + block shapes from the shared source of truth the
    # autotuner's VMEM predicate prices (ops/autotune.py)
    geom = autotune.hist_geometry(F=F, B=B, W=W, F_rows=bins_t.shape[0])
    Bp, group_sz, gb = geom["Bp"], geom["group_sz"], geom["gb"]
    groups, gb_pad = geom["groups"], geom["gb_pad"]

    pad = (-n) % chunk
    if pad:
        bins_t = jnp.pad(bins_t, ((0, 0), (0, pad)))
        g = jnp.pad(g, (0, pad))
        h = jnp.pad(h, (0, pad))
        sample_mask = jnp.pad(sample_mask, (0, pad))
        leaf_ids = jnp.pad(leaf_ids, (0, pad), constant_values=-1)
    n_pad = n + pad

    ghm = jnp.stack([
        g.astype(jnp.float32), h.astype(jnp.float32),
        sample_mask.astype(jnp.float32),
        jnp.zeros_like(g, jnp.float32)], axis=0)          # [4, N]
    leaf2d = leaf_ids.astype(jnp.int32)[None, :]          # [1, N]
    # transposed table: row k = slot k, col j = field j
    tblT = jnp.pad(tbl.astype(jnp.int32).T,
                   ((0, 128 - W), (0, TBL_ROWS - tbl.shape[0])),
                   constant_values=-1)                     # [128, 16]

    kernel = functools.partial(
        _fused_kernel, F=F, B=B, W=W, groups=groups, group_sz=group_sz,
        variant=variant, exact_dot=interpret and not int8, int8=int8,
        any_cat=any_cat, count_proxy=count_proxy, packed4=packed4)

    blk = autotune.fused_hist_block_shapes(chunk=chunk, geom=geom,
                                           tbl_rows=TBL_ROWS)
    out_specs = [
        pl.BlockSpec(blk["hist"], lambda i: (0, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec(blk["leaf_out"], lambda i: (0, i),
                     memory_space=pltpu.VMEM),
    ]
    out_shape = [
        jax.ShapeDtypeStruct(blk["hist"],
                             jnp.int32 if int8 else jnp.float32),
        jax.ShapeDtypeStruct((1, n_pad), jnp.int32),
    ]
    if count_proxy:
        out_specs.append(pl.BlockSpec(blk["cnt"], lambda i: (0, 0),
                                      memory_space=pltpu.VMEM))
        out_shape.append(jax.ShapeDtypeStruct(blk["cnt"], jnp.float32))
    elif variant == "hilo4":
        # second histogram-shaped accumulator: the count-dot channels
        out_specs.append(pl.BlockSpec(blk["hist"], lambda i: (0, 0, 0),
                                      memory_space=pltpu.VMEM))
        out_shape.append(jax.ShapeDtypeStruct(blk["hist"], jnp.float32))
    outs = pl.pallas_call(
        kernel,
        grid=(n_pad // chunk,),
        in_specs=[
            pl.BlockSpec(blk["tbl"], lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(blk["bins"], lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(blk["ghm"], lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(blk["leaf"], lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=tuple(out_specs),
        out_shape=tuple(out_shape),
        compiler_params=autotune.tpu_compiler_params(),
        interpret=interpret,
    )(tblT, bins_t, ghm, leaf2d)
    hist, leaf_out = outs[0], outs[1]

    # [groups, gb_pad, 128] -> [F, B, nchan*W] -> [W, F, B, nchan'].
    # channel rows were [c*W + k]: reshape (nchan, W) then combine
    # (feature rows sit at the aligned Bp stride; slice back to B)
    hist = hist[:, :gb, :nchan * W].reshape(
        groups * group_sz, Bp, nchan * W)[:F, :B]
    hist = hist.reshape(F, B, nchan, W)
    if count_proxy:
        hist = hist.transpose(0, 1, 3, 2)                  # [F,B,W,2]
        if dequant:
            hist = hist.astype(jnp.float32) \
                * jnp.stack([jnp.float32(gh_scale[0]),
                             jnp.float32(gh_scale[1])])
        return (leaf_out[0, :n], hist.transpose(2, 0, 1, 3),
                outs[2][:W, 0])
    if int8:
        hist = hist.transpose(0, 1, 3, 2)                  # [F,B,W,3]
        if dequant:
            hist = hist.astype(jnp.float32) * _qscale_vec(gh_scale)
        return leaf_out[0, :n], hist.transpose(2, 0, 1, 3)
    if variant == "hilo5":
        hist = jnp.stack([hist[:, :, 0] + hist[:, :, 1],   # g = hi+lo
                          hist[:, :, 2] + hist[:, :, 3],   # h = hi+lo
                          hist[:, :, 4]], axis=2)          # count
    elif variant == "hilo4":
        cnt = outs[2][:, :gb, :W].reshape(
            groups * group_sz, Bp, W)[:F, :B]              # [F, B, W]
        hist = jnp.stack([hist[:, :, 0] + hist[:, :, 1],   # g = hi+lo
                          hist[:, :, 2] + hist[:, :, 3],   # h = hi+lo
                          cnt], axis=2)                    # count (dot 2)
    elif variant == "hilo3":
        hist = jnp.stack([hist[:, :, 0] + hist[:, :, 1],   # g = hi+lo
                          hist[:, :, 2],                   # h = count
                          hist[:, :, 2]], axis=2)          # count
    else:
        hist = jnp.stack([hist[:, :, 0] + hist[:, :, 1],   # g = hi+lo
                          hist[:, :, 2],                   # h (bf16)
                          hist[:, :, 3]], axis=2)          # count
    return leaf_out[0, :n], hist.transpose(3, 0, 1, 2)


# ---------------------------------------------------------------------------
# Pallas GPU (Triton) kernels
# ---------------------------------------------------------------------------
#
# The GPU port keeps the SAME public contracts as the TPU kernels but a
# completely different accumulation strategy: there is no MXU to feed,
# so the one-hot matmul design would waste the device — instead the
# histogram lives in GLOBAL memory and every (row, feature) contributes
# via atomic adds (the canonical Triton histogram idiom, and the same
# per-workgroup scatter shape as the reference's
# ocl/histogram256.cl device kernels). Consequences:
#
# - No 128-lane budget: every hilo channel layout accumulates the full
#   f32 (or int32) value per channel, so "hilo5"/"hilo4"/"hilo3" all
#   lower to the SAME kernel (the variant only matters upstream, where
#   it sets the wave-width cap). No bf16 hi/lo split, no wave caps.
# - Bit-equality with the XLA oracle holds by ORDER: each histogram
#   cell receives its adds in increasing global row order (grid blocks
#   ascend, the in-block row loop ascends, and a cell is touched by
#   exactly one feature), which is exactly the order XLA's scatter-add
#   applies duplicate updates in. In interpret mode (grid steps
#   sequential) this makes every output BIT-equal to the oracle — the
#   tier-1 parity proof. On a real GPU, CTAs race: f32 sums can
#   reassociate run-to-run, while the int8 tier's int32 adds are
#   order-free and stay exact (the reason the quantized tier is the
#   recommended GPU configuration).
# - Zero-init rides input_output_aliases with a pre-zeroed operand
#   (NOT a step-0 in-kernel zero, which would race the other CTAs'
#   atomics on a real device).
#
# Out-of-wave rows land in a DUMP slot (index W) that is allocated and
# sliced off — the GPU analog of the oracle's mode="drop" sentinel.


def _gpu_unpack_row(bins_ref, r, F, packed4):
    """Row ``r``'s logical per-feature bin vector [F] i32 — nibble
    unpack for the 4-bit tier (feature 2p in the LOW nibble of byte
    row p, matching _feature_row)."""
    i32 = jnp.int32
    if not packed4:
        return bins_ref[:, r].astype(i32)
    packed = bins_ref[:, r].astype(i32)                   # [ceil(F/2)]
    f_iota = jax.lax.broadcasted_iota(i32, (F,), 0)
    byte = packed[f_iota // 2]
    return jnp.where(f_iota % 2 == 1,
                     jax.lax.shift_right_logical(byte, 4),
                     jnp.bitwise_and(byte, 15))


def _gpu_wave_kernel(wl_ref, bins_ref, g_ref, h_ref, leaf_ref,
                     hist0_ref, hist_ref, *, F, B, W, chunk,
                     int8, count_proxy, packed4):
    """One grid block = one row chunk; per row, one atomic-add per
    channel over the F distinct flat targets
    ``slot*F*B + f*B + bin_f`` (out-of-wave rows -> the dump slot W).

    wl_ref:   [W] i32 wave leaf ids (-1 = inactive)
    bins_ref: [F_rows, chunk] feature-major bins
    g/h_ref:  [chunk] f32 (int8 tier: integer-valued)
    leaf_ref: [chunk] i32 leaf ids (-1 = out of bag / padding)
    hist_ref: [(W+1)*F*B, C] flat accumulator (aliased to the
              pre-zeroed hist0_ref input; C = 2 with count_proxy)
    """
    del hist0_ref                      # aliased: its values ARE hist_ref
    i32 = jnp.int32
    wl = wl_ref[...]                                      # [W]
    offs = jax.lax.broadcasted_iota(i32, (F,), 0) * B     # [F]

    def body(r, carry):
        lid = leaf_ref[r]
        eq = (wl == lid) & (wl >= 0)
        fnd = jnp.any(eq)
        slot = jnp.where(fnd, jnp.argmax(eq).astype(i32), W)
        flat = slot * (F * B) + offs + _gpu_unpack_row(
            bins_ref, r, F, packed4)                      # [F] distinct
        if int8:
            gq = jnp.full((F,), g_ref[r].astype(i32))
            hq = jnp.full((F,), h_ref[r].astype(i32))
            pl.atomic_add(hist_ref, (flat, 0), gq)
            pl.atomic_add(hist_ref, (flat, 1), hq)
            if not count_proxy:
                pl.atomic_add(hist_ref, (flat, 2),
                              jnp.full((F,), jnp.int32(1)))
        else:
            pl.atomic_add(hist_ref, (flat, 0),
                          jnp.full((F,), g_ref[r]))
            pl.atomic_add(hist_ref, (flat, 1),
                          jnp.full((F,), h_ref[r]))
            pl.atomic_add(hist_ref, (flat, 2),
                          jnp.full((F,), jnp.float32(1.0)))
        return carry

    jax.lax.fori_loop(0, chunk, body, 0)


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "chunk", "interpret",
                                    "precision", "count_proxy",
                                    "packed4", "num_features",
                                    "dequant", "variant"))
def wave_histogram_pallas_gpu(bins_t, g, h, leaf_ids, wave_leaves, *,
                              num_bins, chunk=0, interpret=False,
                              precision="highest", gh_scale=None,
                              count_proxy=False, packed4=False,
                              num_features=None, dequant=True,
                              variant="hilo5"):
    """Pallas-Triton wave histogram — same contract (and, in interpret
    mode, same BITS) as wave_histogram_xla / wave_histogram_pallas.

    precision="highest"/"default" both accumulate full f32 per channel
    (no lane budget to ration — see the section comment; ``variant``
    is accepted for interface parity and ignored). precision="int8"
    accumulates the pre-quantized integer g/h in int32 — atomically
    ORDER-FREE, so exact on a real GPU too — and ``gh_scale``
    dequantizes (``dequant=False`` returns the raw int32 sums, the
    quantized-psum wire format). count_proxy (int8 only) drops the
    count channel like the TPU kernel: [W, F, B, 2] out.
    """
    del variant                        # layout-free on GPU
    F, n = bins_t.shape
    if packed4:
        if num_bins > 16:
            raise NotImplementedError("packed4 needs max_bin <= 16")
        F = int(num_features)
    W = int(wave_leaves.shape[0])
    B = num_bins
    int8 = precision == "int8"
    if count_proxy and not int8:
        raise NotImplementedError("count_proxy requires precision='int8'")
    chunk = chunk or autotune.DEFAULT_GPU_HIST_CHUNK
    if int8 and 127 * (n + (-n) % chunk) >= 2 ** 31:
        raise NotImplementedError(
            "int8 histogram sums could overflow int32 beyond ~16.9M "
            "rows; disable tpu_quantized_hist")

    pad = (-n) % chunk
    if pad:
        bins_t = jnp.pad(bins_t, ((0, 0), (0, pad)))
        g = jnp.pad(g, (0, pad))
        h = jnp.pad(h, (0, pad))
        leaf_ids = jnp.pad(leaf_ids, (0, pad), constant_values=-1)
    n_pad = n + pad

    C = 2 if count_proxy else 3
    acc_dt = jnp.int32 if int8 else jnp.float32
    size = (W + 1) * F * B                       # + the dump slot
    hist0 = jnp.zeros((size, C), acc_dt)
    F_rows = bins_t.shape[0]

    kernel = functools.partial(
        _gpu_wave_kernel, F=F, B=B, W=W, chunk=chunk, int8=int8,
        count_proxy=count_proxy, packed4=packed4)

    hist = pl.pallas_call(
        kernel,
        grid=(n_pad // chunk,),
        in_specs=[
            pl.BlockSpec((W,), lambda i: (0,)),
            pl.BlockSpec((F_rows, chunk), lambda i: (0, i)),
            pl.BlockSpec((chunk,), lambda i: (i,)),
            pl.BlockSpec((chunk,), lambda i: (i,)),
            pl.BlockSpec((chunk,), lambda i: (i,)),
            pl.BlockSpec((size, C), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((size, C), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((size, C), acc_dt),
        input_output_aliases={5: 0},
        compiler_params=(None if interpret
                         else autotune.gpu_compiler_params()),
        interpret=interpret,
    )(wave_leaves.astype(jnp.int32), bins_t,
      g.astype(jnp.float32), h.astype(jnp.float32),
      leaf_ids.astype(jnp.int32), hist0)

    out = hist[:W * F * B].reshape(W, F, B, C)
    if int8:
        if not dequant:
            return out
        if count_proxy:
            return out.astype(jnp.float32) * jnp.stack(
                [jnp.float32(gh_scale[0]), jnp.float32(gh_scale[1])])
        return out.astype(jnp.float32) * _qscale_vec(gh_scale)
    return out


def _gpu_fused_kernel(tbl_ref, bins_ref, g_ref, h_ref, mask_ref,
                      leaf_ref, hist0_ref, cnt0_ref, hist_ref,
                      leaf_out_ref, cnt_ref, *, F, B, W, chunk,
                      int8, any_cat, count_proxy, packed4):
    """One grid block: partition one row chunk by the wave's W splits
    (vectorized compare math, bit-identical to ops/partition.py
    row_goes_right — the same logical forms as the TPU _fused_kernel),
    then scatter the wave's smaller-child histograms with per-row
    atomic adds.

    tbl_ref: [18, W] i32 packed split table (TBL_* ROWS — the GPU
    kernel reads the table in its natural orientation; no 128-lane
    transpose). cnt_ref: [W] f32 per-slot moved-row counts (aliased
    pre-zeroed; count_proxy only — a 1-element stub otherwise).
    """
    del hist0_ref, cnt0_ref            # aliased pre-zeroed operands
    i32 = jnp.int32
    leaf = leaf_ref[...]                                   # [chunk]
    parent = tbl_ref[TBL_PARENT, :]                        # [W]
    new_ids = tbl_ref[TBL_NEW, :]
    feat = tbl_ref[TBL_FEAT, :]
    tbin = tbl_ref[TBL_BIN, :]
    dleft = tbl_ref[TBL_DLEFT, :]
    miss = tbl_ref[TBL_MISS, :]
    defb = tbl_ref[TBL_DEFBIN, :]
    nb = tbl_ref[TBL_NUMBIN, :]
    small = tbl_ref[TBL_SMALL, :]
    iscat = tbl_ref[TBL_ISCAT, :]

    # ---- vectorized partition, [W, chunk] orientation ----
    safe_feat = jnp.maximum(feat, 0)
    if packed4:
        packed = bins_ref[safe_feat // 2, :].astype(i32)   # [W, chunk]
        cols = jnp.where((safe_feat % 2 == 1)[:, None],
                         jax.lax.shift_right_logical(packed, 4),
                         jnp.bitwise_and(packed, 15))
    else:
        cols = bins_ref[safe_feat, :].astype(i32)          # [W, chunk]
    na_sent = jnp.where(miss == 2, nb - 1, -9)[:, None]
    def_sent = jnp.where(miss == 1, defb, -9)[:, None]
    is_missing = (cols == na_sent) | (cols == def_sent)
    gt = cols > tbin[:, None]
    ndl = (dleft == 0)[:, None]
    right = gt ^ (is_missing & (gt ^ ndl))
    if any_cat:
        widx = jnp.right_shift(cols, 5)
        word = jnp.zeros_like(cols)
        for wq in range(8):
            word = jnp.where(widx == wq,
                             tbl_ref[TBL_CATW + wq, :][:, None], word)
        cat_left = jnp.bitwise_and(
            jnp.right_shift(word, jnp.bitwise_and(cols, 31)), 1) != 0
        iscat_b = (iscat > 0)[:, None]
        right = (iscat_b & ~cat_left) | (~iscat_b & right)
    eq = (leaf[None, :] == parent[:, None]) \
        & (parent >= 0)[:, None]                           # [W, chunk]
    moved = eq & right
    dest1 = jnp.sum(jnp.where(moved, (new_ids + 1)[:, None], 0), axis=0)
    leaf_new = jnp.where(dest1 > 0, dest1 - 1, leaf).astype(i32)
    leaf_out_ref[...] = leaf_new

    in_bag = mask_ref[...] > 0                             # [chunk]
    small_right = small == new_ids                         # [W]
    if count_proxy:
        # exact per-slot moved-row counts (f32 0/1 sums are integer-
        # valued -> order-free exact, atomics or not)
        s = jnp.sum((moved & in_bag[None, :]).astype(jnp.float32),
                    axis=1)                                # [W]
        pl.atomic_add(cnt_ref,
                      (jax.lax.broadcasted_iota(i32, (W,), 0),), s)

    # ---- per-row atomic histogram scatter ----
    offs = jax.lax.broadcasted_iota(i32, (F,), 0) * B      # [F]

    def body(r, carry):
        memb = (eq[:, r] & (moved[:, r] == small_right)
                & (small >= 0) & in_bag[r])
        fnd = jnp.any(memb)
        slot = jnp.where(fnd, jnp.argmax(memb).astype(i32), W)
        flat = slot * (F * B) + offs + _gpu_unpack_row(
            bins_ref, r, F, packed4)
        if int8:
            pl.atomic_add(hist_ref, (flat, 0),
                          jnp.full((F,), g_ref[r].astype(i32)))
            pl.atomic_add(hist_ref, (flat, 1),
                          jnp.full((F,), h_ref[r].astype(i32)))
            if not count_proxy:
                pl.atomic_add(hist_ref, (flat, 2),
                              jnp.full((F,), jnp.int32(1)))
        else:
            pl.atomic_add(hist_ref, (flat, 0),
                          jnp.full((F,), g_ref[r]))
            pl.atomic_add(hist_ref, (flat, 1),
                          jnp.full((F,), h_ref[r]))
            pl.atomic_add(hist_ref, (flat, 2),
                          jnp.full((F,), jnp.float32(1.0)))
        return carry

    jax.lax.fori_loop(0, chunk, body, 0)


@functools.partial(jax.jit, static_argnames=("num_bins", "chunk",
                                             "interpret", "precision",
                                             "any_cat", "count_proxy",
                                             "packed4", "num_features",
                                             "dequant", "variant"))
def fused_partition_histogram_pallas_gpu(bins_t, g, h, sample_mask,
                                         leaf_ids, tbl, *, num_bins,
                                         chunk=0, interpret=False,
                                         precision="highest",
                                         gh_scale=None, any_cat=True,
                                         count_proxy=False,
                                         packed4=False,
                                         num_features=None,
                                         dequant=True,
                                         variant="hilo5"):
    """Pallas-Triton twin of fused_partition_histogram_pallas: same
    contract, and in interpret mode the same BITS as
    fused_partition_histogram_xla. Returns (new_leaf_ids [N],
    hist [W, F, B, 3]) — with ``count_proxy``, (new_leaf_ids,
    hist [W, F, B, 2], cnt_right [W]).

    No wave-width caps: the atomic scatter has no 128-lane budget, so
    every ``variant`` lowers to the same kernel (accepted for
    interface parity). The partition math is the exact integer/compare
    sequence of the TPU kernel and the XLA oracle — bit-identical by
    construction; the histogram's bit-equality argument is the
    row-order one in the section comment.
    """
    del variant                        # layout-free on GPU
    F, n = bins_t.shape
    if packed4:
        if num_bins > 16:
            raise NotImplementedError("packed4 needs max_bin <= 16")
        F = int(num_features)
    W = int(tbl.shape[1])
    B = num_bins
    int8 = precision == "int8"
    if count_proxy and not int8:
        raise NotImplementedError("count_proxy requires precision='int8'")
    chunk = chunk or autotune.DEFAULT_GPU_HIST_CHUNK
    if int8 and 127 * (n + (-n) % chunk) >= 2 ** 31:
        raise NotImplementedError(
            "int8 histogram sums could overflow int32 beyond ~16.9M "
            "rows; disable tpu_quantized_hist")

    pad = (-n) % chunk
    if pad:
        bins_t = jnp.pad(bins_t, ((0, 0), (0, pad)))
        g = jnp.pad(g, (0, pad))
        h = jnp.pad(h, (0, pad))
        sample_mask = jnp.pad(sample_mask, (0, pad))
        leaf_ids = jnp.pad(leaf_ids, (0, pad), constant_values=-1)
    n_pad = n + pad

    C = 2 if count_proxy else 3
    acc_dt = jnp.int32 if int8 else jnp.float32
    size = (W + 1) * F * B                       # + the dump slot
    hist0 = jnp.zeros((size, C), acc_dt)
    # count accumulator (1-element stub when unused: pallas wants a
    # static operand list, and the kernel never touches the stub)
    cnt0 = jnp.zeros((W if count_proxy else 1,), jnp.float32)
    F_rows = bins_t.shape[0]
    tbl18 = tbl.astype(jnp.int32)                # [18, W], natural

    kernel = functools.partial(
        _gpu_fused_kernel, F=F, B=B, W=W, chunk=chunk, int8=int8,
        any_cat=any_cat, count_proxy=count_proxy, packed4=packed4)

    outs = pl.pallas_call(
        kernel,
        grid=(n_pad // chunk,),
        in_specs=[
            pl.BlockSpec(tbl18.shape, lambda i: (0, 0)),
            pl.BlockSpec((F_rows, chunk), lambda i: (0, i)),
            pl.BlockSpec((chunk,), lambda i: (i,)),
            pl.BlockSpec((chunk,), lambda i: (i,)),
            pl.BlockSpec((chunk,), lambda i: (i,)),
            pl.BlockSpec((chunk,), lambda i: (i,)),
            pl.BlockSpec((size, C), lambda i: (0, 0)),
            pl.BlockSpec(cnt0.shape, lambda i: (0,)),
        ],
        out_specs=(
            pl.BlockSpec((size, C), lambda i: (0, 0)),
            pl.BlockSpec((chunk,), lambda i: (i,)),
            pl.BlockSpec(cnt0.shape, lambda i: (0,)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((size, C), acc_dt),
            jax.ShapeDtypeStruct((n_pad,), jnp.int32),
            jax.ShapeDtypeStruct(cnt0.shape, jnp.float32),
        ),
        input_output_aliases={6: 0, 7: 2},
        compiler_params=(None if interpret
                         else autotune.gpu_compiler_params()),
        interpret=interpret,
    )(tbl18, bins_t, g.astype(jnp.float32), h.astype(jnp.float32),
      sample_mask.astype(jnp.float32), leaf_ids.astype(jnp.int32),
      hist0, cnt0)
    hist, leaf_out, cnt = outs

    hist = hist[:W * F * B].reshape(W, F, B, C)
    if count_proxy:
        if dequant:
            hist = hist.astype(jnp.float32) * jnp.stack(
                [jnp.float32(gh_scale[0]), jnp.float32(gh_scale[1])])
        return leaf_out[:n], hist, cnt[:W]
    if int8:
        if dequant:
            hist = hist.astype(jnp.float32) * _qscale_vec(gh_scale)
        return leaf_out[:n], hist
    if gh_scale is not None and dequant:
        hist = hist * _qscale_vec(gh_scale)
    return leaf_out[:n], hist
