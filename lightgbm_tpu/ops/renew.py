"""On-device objective-driven leaf output renewal.

TPU-native counterpart of RenewTreeOutput for the L1-family objectives
(reference: src/treelearner/serial_tree_learner.cpp:780-818 calls
ObjectiveFunction::RenewTreeOutput, which computes residual percentiles
per leaf — PercentileFun / WeightedPercentileFun,
src/objective/regression_objective.hpp:11-60).

Instead of per-leaf host loops, ONE lexicographic device sort by
(leaf_id, residual) makes every leaf's residuals a contiguous sorted
segment; per-leaf percentiles are then dynamic-slice gathers, vmapped
over leaves. No host transfer.

Percentile semantics follow the reference:
- unweighted: float_pos = (1-alpha)*cnt from the TOP of the sorted order
  with linear interpolation (regression_objective.hpp:16-35).
- weighted: weighted-CDF threshold alpha*total, interpolated between the
  two bracketing values. (The reference's macro indexes cdf[pos+1] which
  can read one past the end — we use the standard bracketing
  cdf[pos-1]..cdf[pos] instead, which is what the formula intends.)
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("num_leaves", "alpha",
                                             "weighted"))
def _renew(leaf_ids, residual, weights, cur_outputs, *, num_leaves: int,
           alpha: float, weighted: bool):
    n = residual.shape[0]
    f32 = jnp.float32
    lid = leaf_ids.astype(jnp.int32)
    res = residual.astype(f32)
    w = weights.astype(f32)
    # rows with zero weight (OOB under bagging) sort after every real row
    dead = w <= 0.0
    key = jnp.where(dead, num_leaves, lid)
    sorted_key, sorted_res, sorted_w = jax.lax.sort(
        (key, res, w), num_keys=2)

    counts = jnp.bincount(jnp.where(dead, num_leaves, lid),
                          weights=jnp.ones(n, f32),
                          length=num_leaves + 1)[:num_leaves]
    starts = jnp.concatenate(
        [jnp.zeros(1, f32), jnp.cumsum(counts)])[:num_leaves]
    starts = starts.astype(jnp.int32)
    counts = counts.astype(jnp.int32)

    idx = jnp.arange(n, dtype=jnp.int32)

    def one_leaf(start, cnt, cur):
        # positions within this leaf's segment: [start, start+cnt)
        def val_at(i):
            # residual at within-leaf sorted ascending index i (clipped)
            j = jnp.clip(start + i, 0, n - 1)
            return sorted_res[j]

        if not weighted:
            fp = (1.0 - alpha) * cnt.astype(f32)
            pos = jnp.floor(fp).astype(jnp.int32)
            bias = fp - pos.astype(f32)
            vmax = val_at(cnt - 1)
            vmin = val_at(0)
            # descending[pos-1] = ascending[cnt-pos]
            v1 = val_at(cnt - pos)
            v2 = val_at(cnt - pos - 1)
            mid = v1 - (v1 - v2) * bias
            out = jnp.where(pos < 1, vmax,
                            jnp.where(pos >= cnt, vmin, mid))
        else:
            in_seg = (idx >= start) & (idx < start + cnt)
            seg_w = jnp.where(in_seg, sorted_w, 0.0)
            cdf = jnp.cumsum(seg_w)
            total = jnp.sum(seg_w)
            thr = alpha * total
            # first global index with cdf > thr inside the segment
            above = (cdf > thr) & in_seg
            pos = jnp.argmax(above)  # first True (0 if none)
            any_above = jnp.any(above)
            pos = jnp.where(any_above, pos, start + cnt - 1)
            i = pos - start
            v1 = val_at(i - 1)
            v2 = val_at(i)
            c1 = cdf[jnp.clip(pos - 1, 0, n - 1)]
            c2 = cdf[jnp.clip(pos, 0, n - 1)]
            t = jnp.where(c2 > c1, (thr - c1) / (c2 - c1), 0.0)
            out = jnp.where(i <= 0, v2, v1 + t * (v2 - v1))
        return jnp.where(cnt > 0, out, cur)

    return jax.vmap(one_leaf)(starts, counts, cur_outputs[:num_leaves])


def renew_leaf_outputs(leaf_ids, residual, weights, num_leaves: int,
                       alpha: float, cur_outputs, sample_mask=None):
    """Replace each leaf's output with the (weighted) alpha-percentile of
    its member residuals; leaves with no members keep ``cur_outputs``."""
    n = residual.shape[0]
    if weights is None:
        w = jnp.ones(n, jnp.float32)
        weighted = False
    else:
        w = weights
        weighted = True
    if sample_mask is not None:
        w = w * sample_mask
    out = _renew(leaf_ids, residual, w, cur_outputs,
                 num_leaves=num_leaves, alpha=float(alpha),
                 weighted=weighted)
    full = cur_outputs
    return full.at[:num_leaves].set(out)
