"""Histogram construction on device.

TPU-native replacement for the reference's histogram kernels
(reference: src/io/dense_bin.hpp:72-130 CPU loops,
src/treelearner/ocl/histogram256.cl:345 OpenCL kernels). Instead of
scatter/atomics — which TPUs lack — histograms are built as a chunked
one-hot contraction that XLA lowers onto the MXU: for each row chunk,
``onehot(bins)`` is contracted against the per-row ``(grad, hess, count)``
triple, mirroring the per-workgroup partial-histogram design of the OpenCL
kernels (gpu_tree_learner.cpp:194-232) with the partial-sum reduction done
by the ``lax.scan`` accumulator.

Layout: ``hist[F, B, 3]`` where channel 0=sum_grad, 1=sum_hess, 2=count.
Counts are float sums of the row mask (bagging masks fold in here, matching
the reference where histograms are built over the bagged subset).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("num_bins", "chunk"))
def build_histogram(bins: jax.Array, w: jax.Array, *, num_bins: int,
                    chunk: int = 16384) -> jax.Array:
    """Build (grad, hess, count) histograms for every feature.

    Args:
      bins: [N, F] integer bin indices (uint8/int32).
      w:    [N, 3] per-row (grad, hess, mask) — mask already multiplied in,
            i.e. w = mask[:, None] * stack([grad, hess, ones], -1).
      num_bins: global padded bin count B (static).
      chunk: rows per MXU pass (static).

    Returns:
      [F, B, 3] float32 histogram.
    """
    n, f = bins.shape
    pad = (-n) % chunk
    if pad:
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        w = jnp.pad(w, ((0, pad), (0, 0)))
    n_pad = n + pad
    n_chunks = n_pad // chunk
    bins_c = bins.astype(jnp.int32).reshape(n_chunks, chunk, f)
    w_c = w.astype(jnp.float32).reshape(n_chunks, chunk, 3)

    def body(acc, args):
        b, wc = args
        # one-hot [chunk, F, B] contracted over rows on the MXU
        oh = jax.nn.one_hot(b, num_bins, dtype=jnp.float32)
        # HIGHEST: default matmul precision truncates f32 operands to
        # bf16 (shape-dependent, CPU XLA included) — this fallback is
        # the exact-histogram oracle, so the raw g/h must not round
        h = jnp.einsum("cfb,cd->fbd", oh, wc,
                       precision=jax.lax.Precision.HIGHEST,
                       preferred_element_type=jnp.float32)
        return acc + h, None

    init = jnp.zeros((f, num_bins, 3), dtype=jnp.float32)
    hist, _ = jax.lax.scan(body, init, (bins_c, w_c))
    return hist


def subtract_histogram(parent: jax.Array, child: jax.Array) -> jax.Array:
    """Sibling histogram by subtraction (feature_histogram.hpp:68)."""
    return parent - child


def fix_histogram_totals(hist: jax.Array, sum_g, sum_h, cnt) -> jax.Array:
    """No-op placeholder for the reference's FixHistogram
    (src/io/dataset.cpp:802): our histograms always carry every bin
    including the default bin, so nothing needs restoring."""
    return hist
