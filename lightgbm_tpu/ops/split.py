"""Vectorized best-split search over histograms.

TPU-native counterpart of FeatureHistogram::FindBestThreshold*
(reference: src/treelearner/feature_histogram.hpp:76-653). The reference
scans each feature's bins twice (right-to-left with missing-default-left,
left-to-right with missing-default-right); here both scans over every
feature are evaluated at once as cumulative sums + masked argmax — an
ideal XLA workload (no data-dependent control flow).

Semantics preserved from the reference:
- L1-thresholded leaf outputs and gains (ThresholdL1 /
  CalculateSplittedLeafOutput / GetLeafSplitGainGivenOutput,
  feature_histogram.hpp:442-504).
- kEpsilon hessian regularization on each accumulated side and
  ``sum_hessian + 2*kEpsilon`` at the parent (feature_histogram.hpp:76-80).
- Missing handling: two-direction scans when ``num_bin > 2`` and missing
  is not None; NaN bin excluded from accumulation (rides with the default
  side); zero(default)-bin skipped when missing type is Zero
  (feature_histogram.hpp:87-110,506-653).
- min_data_in_leaf / min_sum_hessian_in_leaf / min_gain_to_split gates and
  monotone-constraint zeroing (GetSplitGains, feature_histogram.hpp:458).
- Tie-breaking: the flattened argmax order reproduces the reference's
  scan order (feature-major; dir=-1 before dir=+1; within dir=-1 larger
  thresholds win, within dir=+1 smaller thresholds win).
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

KEPSILON = 1e-15            # meta.h:38
KMIN_SCORE = -jnp.inf

MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2

NCAT_WORDS = 8              # 256-bin bitset for categorical left-sets


class SplitParams(NamedTuple):
    """Static (per-training-run) split hyperparameters."""
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    max_delta_step: float = 0.0
    min_data_in_leaf: float = 20.0
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    # categorical search (feature_histogram.hpp:112-234)
    max_cat_to_onehot: int = 4
    max_cat_threshold: int = 32
    cat_l2: float = 10.0
    cat_smooth: float = 10.0
    min_data_per_group: float = 100.0
    # static trace-time gate: False compiles the categorical search out
    # entirely (set per-dataset; numerical-only runs pay nothing)
    has_cat: bool = True
    # count-proxy mode (tpu_count_proxy): the histogram count channel
    # carries per-bin LOWER BOUNDS, not exact counts. Both sides of the
    # min_data_in_leaf gate must then come from prefix/suffix sums of
    # the channel itself (a sum of lower bounds is a lower bound) —
    # deriving one side as num_data - other_side would turn an
    # under-estimate into an over-estimate and let min_data violations
    # through. Conservative: never under-prunes, may over-prune.
    count_lb: bool = False


class FeatureMeta(NamedTuple):
    """Per-feature bin metadata as device arrays (host numpy accepted)."""
    num_bin: jax.Array       # [F] int32
    missing_type: jax.Array  # [F] int32
    default_bin: jax.Array   # [F] int32
    monotone: jax.Array      # [F] int32 (-1, 0, +1)
    penalty: jax.Array       # [F] float32 (feature_contri; 1.0 default)
    # 1 = categorical (bin.h BinType); scalar-0 default broadcasts so
    # numerical-only constructors don't need the field
    is_cat: jax.Array = np.zeros((), np.int32)
    # EFB (io/efb.py): member feature -> bundle column + bin offset.
    # Scalar sentinel = identity (no bundling); shapes are trace-static
    # so the decode compiles away entirely when unbundled.
    bundle: jax.Array = np.zeros((), np.int32)
    offset: jax.Array = np.zeros((), np.int32)

    @classmethod
    def from_mappers(cls, mappers, monotone_constraints=None,
                     feature_contri=None) -> "FeatureMeta":
        f = len(mappers)
        mono = np.zeros(f, np.int32)
        if monotone_constraints:
            mono[:len(monotone_constraints)] = monotone_constraints
        pen = np.ones(f, np.float32)
        if feature_contri:
            pen[:len(feature_contri)] = feature_contri
        return cls(
            num_bin=np.array([m.num_bin for m in mappers], np.int32),
            missing_type=np.array([m.missing_type for m in mappers], np.int32),
            default_bin=np.array([m.default_bin for m in mappers], np.int32),
            monotone=mono,
            penalty=pen,
            is_cat=np.array([1 if m.bin_type == 1 else 0
                             for m in mappers], np.int32),
        )


class SplitResult(NamedTuple):
    """Best split for one leaf — all scalars except the categorical
    left-set bitset (SplitInfo analog, src/treelearner/split_info.hpp:17;
    cat_threshold split_info.hpp:28)."""
    gain: jax.Array
    feature: jax.Array
    threshold_bin: jax.Array
    default_left: jax.Array
    left_output: jax.Array
    right_output: jax.Array
    left_count: jax.Array
    right_count: jax.Array
    left_sum_g: jax.Array
    left_sum_h: jax.Array
    right_sum_g: jax.Array
    right_sum_h: jax.Array
    is_cat: jax.Array = np.zeros((), bool)
    # [NCAT_WORDS] int32 bitset over BIN ids: set bit = bin goes LEFT
    cat_words: jax.Array = np.zeros(NCAT_WORDS, np.int32)


def threshold_l1(s, l1):
    """ThresholdL1 (feature_histogram.hpp:442)."""
    return jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0)


def calculate_leaf_output(sum_g, sum_h, l1, l2, max_delta_step):
    """CalculateSplittedLeafOutput (feature_histogram.hpp:447)."""
    ret = -threshold_l1(sum_g, l1) / (sum_h + l2)
    if max_delta_step > 0.0:
        ret = jnp.clip(ret, -max_delta_step, max_delta_step)
    return ret


def leaf_split_gain_given_output(sum_g, sum_h, l1, l2, output):
    """GetLeafSplitGainGivenOutput (feature_histogram.hpp:500)."""
    sg_l1 = threshold_l1(sum_g, l1)
    return -(2.0 * sg_l1 * output + (sum_h + l2) * output * output)


def leaf_split_gain(sum_g, sum_h, l1, l2, max_delta_step):
    """GetLeafSplitGain (feature_histogram.hpp:495)."""
    out = calculate_leaf_output(sum_g, sum_h, l1, l2, max_delta_step)
    return leaf_split_gain_given_output(sum_g, sum_h, l1, l2, out)


def _candidate_tables(hist: jax.Array, sum_g, sum_h, num_data,
                      feature_mask: jax.Array, meta: FeatureMeta,
                      hp: SplitParams, can_split=True):
    """Gain tables for every (feature, direction, threshold) candidate.

    Returns (g2, g1, min_gain_shift, ctx) where g2/g1 are the masked
    gain tables [F, B] for dir=-1 / dir=+1 and ctx carries the
    left-accumulation arrays needed to reconstruct a SplitResult.
    """
    f32 = jnp.float32
    F, B, _ = hist.shape
    nb = meta.num_bin.astype(jnp.int32)            # [F]
    mt = meta.missing_type.astype(jnp.int32)       # [F]
    db = meta.default_bin.astype(jnp.int32)        # [F]
    mono = meta.monotone.astype(jnp.int32)         # [F]

    l1 = f32(hp.lambda_l1)
    l2 = f32(hp.lambda_l2)
    mds = float(hp.max_delta_step)

    sum_g = jnp.asarray(sum_g, f32)
    sum_h2 = jnp.asarray(sum_h, f32) + 2.0 * KEPSILON   # hpp:80
    num_data = jnp.asarray(num_data, f32)

    gain_shift = leaf_split_gain(sum_g, sum_h2, l1, l2, mds)
    min_gain_shift = gain_shift + f32(hp.min_gain_to_split)

    bidx = jnp.arange(B, dtype=jnp.int32)[None, :]  # [1, B]
    nb_c = nb[:, None]
    two_scan = (nb > 2) & (mt != MISSING_NONE)      # [F]
    use_na = two_scan & (mt == MISSING_NAN)
    skip_db = two_scan & (mt == MISSING_ZERO)

    # --- contributions entering the cumulative scans --------------------
    valid_bin = bidx < nb_c
    zero_bin = (skip_db[:, None] & (bidx == db[:, None]))
    nan_bin = (use_na[:, None] & (bidx == nb_c - 1))
    contrib_mask = (valid_bin & ~zero_bin & ~nan_bin).astype(f32)  # [F, B]
    contrib = hist * contrib_mask[:, :, None]                      # [F, B, 3]

    # prefix sums as a lower-triangular matmul: one MXU pass instead
    # of a lane-shift cumsum chain (prefix-sum = tril @ x)
    tril = jnp.tril(jnp.ones((B, B), f32))
    cum = jnp.einsum("bk,fkc->fbc", tril, contrib,
                     precision=jax.lax.Precision.HIGHEST)  # [F, B, 3]
    tot = cum[:, -1, :]                             # [F, 3]

    # --- dir = +1 : left accumulates from bin 0 (default right) ---------
    l_g1 = cum[:, :, 0]
    l_h1 = cum[:, :, 1] + KEPSILON
    l_c1 = cum[:, :, 2]
    r_g1 = sum_g - l_g1
    r_h1 = sum_h2 - l_h1
    # count_lb: the right-side count must be the SUFFIX sum of the
    # (lower-bound) channel, not num_data - prefix (see SplitParams)
    r_c1 = (tot[:, None, 2] - l_c1) if hp.count_lb else num_data - l_c1
    valid1 = (two_scan[:, None]
              & (bidx <= nb_c - 2)
              & ~(skip_db[:, None] & (bidx == db[:, None])))

    # --- dir = -1 : right accumulates from the top (default left) ------
    r_g2 = tot[:, None, 0] - cum[:, :, 0]
    r_h2 = tot[:, None, 1] - cum[:, :, 1] + KEPSILON
    r_c2 = tot[:, None, 2] - cum[:, :, 2]
    l_g2 = sum_g - r_g2
    l_h2 = sum_h2 - r_h2
    l_c2 = cum[:, :, 2] if hp.count_lb else num_data - r_c2
    max_t2 = jnp.where(use_na, nb - 3, nb - 2)[:, None]  # dir=-1 can't emit nb-2
    valid2 = ((bidx <= max_t2)
              & (bidx >= 0)
              & ~(skip_db[:, None] & (bidx == db[:, None] - 1)))

    def side_gains(lg, lh, rg, rh):
        lo = calculate_leaf_output(lg, lh, l1, l2, mds)
        ro = calculate_leaf_output(rg, rh, l1, l2, mds)
        bad_mono = (((mono[:, None] > 0) & (lo > ro))
                    | ((mono[:, None] < 0) & (lo < ro)))
        g = (leaf_split_gain_given_output(lg, lh, l1, l2, lo)
             + leaf_split_gain_given_output(rg, rh, l1, l2, ro))
        return jnp.where(bad_mono, 0.0, g)

    def constraints(lc, lh, rc, rh):
        return ((lc >= hp.min_data_in_leaf) & (rc >= hp.min_data_in_leaf)
                & (lh >= hp.min_sum_hessian_in_leaf)
                & (rh >= hp.min_sum_hessian_in_leaf))

    gains1 = side_gains(l_g1, l_h1, r_g1, r_h1)
    ok1 = valid1 & constraints(l_c1, l_h1, r_c1, r_h1) & (gains1 > min_gain_shift)
    gains2 = side_gains(l_g2, l_h2, r_g2, r_h2)
    ok2 = valid2 & constraints(l_c2, l_h2, r_c2, r_h2) & (gains2 > min_gain_shift)

    ic = jnp.broadcast_to(jnp.asarray(meta.is_cat, jnp.int32), (F,)) > 0
    fmask = feature_mask[:, None] & can_split & ~ic[:, None]
    g1 = jnp.where(ok1 & fmask, gains1, KMIN_SCORE)
    g2 = jnp.where(ok2 & fmask, gains2, KMIN_SCORE)
    ctx = dict(l_g1=l_g1, l_h1=l_h1, l_c1=l_c1,
               l_g2=l_g2, l_h2=l_h2, l_c2=l_c2,
               sum_g=sum_g, sum_h2=sum_h2, num_data=num_data,
               two_scan=two_scan, mt=mt, l1=l1, l2=l2, mds=mds)
    return g2, g1, min_gain_shift, ctx


def _categorical_tables(hist: jax.Array, sum_g, sum_h2, num_data,
                        feature_mask, meta: FeatureMeta, hp: SplitParams,
                        can_split, min_gain_shift):
    """Categorical split candidates (FindBestThresholdCategorical,
    feature_histogram.hpp:112-234), fully vectorized.

    Returns (gc1, gc2, cat_ctx): gc1 = dir=+1 sorted-prefix gains (and
    the one-hot gains for small-cardinality features), gc2 = dir=-1,
    both [F, B] with -inf where invalid. A feature is one-hot when
    ``num_bin <= max_cat_to_onehot``; otherwise bins with
    ``count >= cat_smooth`` are sorted by g/(h + cat_smooth) and
    prefixes of up to ``max_cat_threshold`` bins are candidates, with
    ``min_data_per_group`` chunking between emitted candidates.
    """
    f32 = jnp.float32
    F, B, _ = hist.shape
    g = hist[:, :, 0]
    h = hist[:, :, 1]
    c = hist[:, :, 2]
    nb = meta.num_bin.astype(jnp.int32)
    mt = meta.missing_type.astype(jnp.int32)
    ic = jnp.broadcast_to(jnp.asarray(meta.is_cat, jnp.int32), (F,)) > 0
    bidx = jnp.arange(B, dtype=jnp.int32)[None, :]

    l1 = f32(hp.lambda_l1)
    l2c = f32(hp.lambda_l2 + hp.cat_l2)
    l2n = f32(hp.lambda_l2)
    mds = float(hp.max_delta_step)
    mdl = f32(hp.min_data_in_leaf)
    msh = f32(hp.min_sum_hessian_in_leaf)
    mdpg = f32(hp.min_data_per_group)

    # candidate category bins (hpp:125-126: the trailing missing bin is
    # excluded unless the feature is "full" / MissingType::None)
    used_bin = nb - 1 + (mt == MISSING_NONE).astype(jnp.int32)  # [F]
    bin_ok = bidx < used_bin[:, None]

    def pair_gain(lg, lh, rg, rh, l2):
        return (leaf_split_gain(lg, lh, l1, l2, mds)
                + leaf_split_gain(rg, rh, l1, l2, mds))

    use_onehot = nb <= hp.max_cat_to_onehot                      # [F]
    fmask = feature_mask & can_split

    # ---- one-hot: left = single bin t (hpp:133-163, plain l2) ----
    lg_o, lh_o, lc_o = g, h + KEPSILON, c
    rg_o = sum_g - g
    rh_o = sum_h2 - lh_o
    rc_o = num_data - c
    gain_o = pair_gain(lg_o, lh_o, rg_o, rh_o, l2n)
    ok_o = (bin_ok & (c >= mdl) & (h >= msh) & (rc_o >= mdl)
            & (rh_o >= msh) & (gain_o > min_gain_shift)
            & ic[:, None] & use_onehot[:, None] & fmask[:, None])
    gain_o = jnp.where(ok_o, gain_o, KMIN_SCORE)

    # ---- sorted k-vs-rest (hpp:164-234, l2 + cat_l2) ----
    elig = bin_ok & (c >= f32(hp.cat_smooth))      # hpp:166 count gate
    ratio = g / (h + f32(hp.cat_smooth))
    ratio = jnp.where(elig, ratio, jnp.inf)        # ineligible sort last
    order = jnp.argsort(ratio, axis=1)             # [F, B]
    rank = jnp.argsort(order, axis=1)              # bin -> sorted pos
    used = jnp.sum(elig.astype(jnp.int32), axis=1)  # [F]
    pos = jnp.arange(B, dtype=jnp.int32)[None, :]
    in_use = pos < used[:, None]

    def sorted_of(x):
        return jnp.where(in_use, jnp.take_along_axis(x, order, axis=1),
                         0.0)
    gs, hs, cs = sorted_of(g), sorted_of(h), sorted_of(c)

    max_num_cat = jnp.minimum(hp.max_cat_threshold,
                              (used + 1) // 2)[:, None]          # [F,1]

    def direction(gd, hd, cd):
        """Candidates for one scan direction over pre-sorted arrays."""
        lg = jnp.cumsum(gd, axis=1)
        lh = jnp.cumsum(hd, axis=1) + KEPSILON
        lc = jnp.cumsum(cd, axis=1)
        rg = sum_g - lg
        rh = sum_h2 - lh
        rc = num_data - lc
        left_ok = (lc >= mdl) & (lh >= msh)
        # right-side failures BREAK the reference scan; both quantities
        # shrink monotonically with i, so the break is a prefix mask
        right_ok = (rc >= mdl) & (rc >= mdpg) & (rh >= msh)
        right_ok = jnp.cumprod(right_ok.astype(jnp.int32),
                               axis=1).astype(bool)
        # min_data_per_group chunking: accumulate counts, emit when the
        # current group reaches mdpg AND the left checks pass, reset on
        # emission (hpp:196-216)
        def step(cnt, xs):
            cn, lok = xs
            cnt = cnt + cn
            emit = lok & (cnt >= mdpg)
            return jnp.where(emit, 0.0, cnt), emit
        _, emits = jax.lax.scan(step, jnp.zeros(F, f32),
                                (cd.T, left_ok.T))
        emit = emits.T
        gain = pair_gain(lg, lh, rg, rh, l2c)
        ok = (emit & right_ok & in_use & (pos < max_num_cat)
              & (gain > min_gain_shift)
              & ic[:, None] & ~use_onehot[:, None] & fmask[:, None])
        return jnp.where(ok, gain, KMIN_SCORE), lg, lh, lc

    gain_p, lg_p, lh_p, lc_p = direction(gs, hs, cs)
    # dir=-1 scans from the LAST eligible position backwards: reverse
    # the eligible block (positions used-1..0). Reversing the masked
    # arrays then re-masking keeps ineligible tail at zero.
    def rev_use(x):
        full = jnp.take_along_axis(
            x, jnp.clip(used[:, None] - 1 - pos, 0, B - 1), axis=1)
        return jnp.where(in_use, full, 0.0)
    gain_m, lg_m, lh_m, lc_m = direction(rev_use(gs), rev_use(hs),
                                         rev_use(cs))

    # one-hot candidates ride the dir=+1 table (a feature is in exactly
    # one mode, so the slots never collide)
    gc1 = jnp.maximum(gain_p, gain_o)
    gc2 = gain_m
    ctx = dict(order=order, rank=rank, used=used, elig=elig,
               use_onehot=use_onehot,
               lg_o=lg_o, lh_o=lh_o, lc_o=lc_o,
               lg_p=lg_p, lh_p=lh_p, lc_p=lc_p,
               lg_m=lg_m, lh_m=lh_m, lc_m=lc_m, l2c=l2c, l2n=l2n)
    return gc1, gc2, ctx


def _cat_left_bitset(fi, t, is_p1, ctx, B):
    """Left-set bitset [NCAT_WORDS] for the winning categorical split."""
    onehot = ctx["use_onehot"][fi]
    rank = ctx["rank"][fi]                 # [B] bin -> sorted pos
    used = ctx["used"][fi]
    elig = ctx["elig"][fi]
    bidx = jnp.arange(B, dtype=jnp.int32)
    member_oh = bidx == t
    member_p1 = (rank <= t) & elig
    member_m1 = (rank >= used - 1 - t) & elig
    member = jnp.where(onehot, member_oh,
                       jnp.where(is_p1, member_p1, member_m1))
    word = bidx // 32
    bit = jnp.left_shift(jnp.uint32(1), (bidx % 32).astype(jnp.uint32))
    contrib = jnp.where(member, bit, jnp.uint32(0))
    words = jnp.zeros(NCAT_WORDS, jnp.uint32).at[word].add(
        contrib, mode="drop")
    return words.astype(jnp.int32)


def best_gain_per_feature(hist, sum_g, sum_h, num_data, feature_mask,
                          meta: FeatureMeta, hp: SplitParams,
                          can_split=True) -> jax.Array:
    """Per-feature best split gain [F] (-inf where no valid split) — the
    local-vote input of the voting-parallel learner
    (VotingParallelTreeLearner, voting_parallel_tree_learner.cpp:166)."""
    g2, g1, min_gain_shift, ctx = _candidate_tables(
        hist, sum_g, sum_h, num_data, feature_mask, meta, hp, can_split)
    best = jnp.maximum(g2.max(axis=1), g1.max(axis=1))
    if hp.has_cat:
        gc1, gc2, _ = _categorical_tables(
            hist, ctx["sum_g"], ctx["sum_h2"], ctx["num_data"],
            feature_mask, meta, hp, can_split, min_gain_shift)
        best = jnp.maximum(best,
                           jnp.maximum(gc1.max(axis=1), gc2.max(axis=1)))
    return jnp.where(jnp.isfinite(best),
                     (best - min_gain_shift) * meta.penalty, KMIN_SCORE)


def find_best_split(hist: jax.Array, sum_g, sum_h, num_data,
                    feature_mask: jax.Array, meta: FeatureMeta,
                    hp: SplitParams, can_split=True) -> SplitResult:
    """Find the best (feature, threshold, direction) for one leaf.

    Args:
      hist: [F, B, 3] histogram (grad, hess, count).
      sum_g/sum_h/num_data: leaf totals (scalars; num_data = bagged count).
      feature_mask: [F] bool — usable features (feature_fraction sampling,
        trivial-feature exclusion).
      can_split: scalar bool gate (e.g. max_depth reached) — forces -inf gain.
    """
    F, B, _ = hist.shape
    g2, g1, min_gain_shift, ctx = _candidate_tables(
        hist, sum_g, sum_h, num_data, feature_mask, meta, hp, can_split)
    if hp.has_cat:
        gc1, gc2, cctx = _categorical_tables(
            hist, ctx["sum_g"], ctx["sum_h2"], ctx["num_data"],
            feature_mask, meta, hp, can_split, min_gain_shift)
        # flatten [F, 4, B]: numerical dir=-1 first with REVERSED
        # thresholds (so larger t wins ties), numerical dir=+1
        # ascending, then the categorical dir=+1 / dir=-1 candidate
        # tables (a feature is either numerical or categorical, so the
        # blocks never compete within one feature). argmax = first max.
        cand = jnp.stack([g2[:, ::-1], g1, gc1, gc2], axis=1)
        nbranch = 4
    else:
        # numerical-only: the 2-branch table of the original design
        # (half the argmax scan; the cat machinery is compiled out)
        cand = jnp.stack([g2[:, ::-1], g1], axis=1)
        cctx = None
        nbranch = 2
    flat = cand.reshape(-1)
    idx = jnp.argmax(flat)
    best_gain = flat[idx]
    fi = idx // (nbranch * B)
    rem = idx % (nbranch * B)
    d = rem // B                  # 0 num dir=-1, 1 num dir=+1, 2/3 cat
    tb = rem % B
    t = jnp.where(d == 0, B - 1 - tb, tb)            # undo reversal

    is_dir2 = d == 0
    is_cat = d >= 2
    cat_p1 = d == 2
    lg = jnp.where(is_dir2, ctx["l_g2"][fi, t], ctx["l_g1"][fi, t])
    lh = jnp.where(is_dir2, ctx["l_h2"][fi, t], ctx["l_h1"][fi, t])
    lc = jnp.where(is_dir2, ctx["l_c2"][fi, t], ctx["l_c1"][fi, t])
    sum_g = ctx["sum_g"]
    sum_h2 = ctx["sum_h2"]
    l1, l2, mds = ctx["l1"], ctx["l2"], ctx["mds"]
    l2_eff = l2
    if hp.has_cat:
        # categorical left sums: one-hot rides the dir=+1 slot
        onehot = cctx["use_onehot"][fi]
        lg_c = jnp.where(cat_p1,
                         jnp.where(onehot, cctx["lg_o"][fi, t],
                                   cctx["lg_p"][fi, t]),
                         cctx["lg_m"][fi, t])
        lh_c = jnp.where(cat_p1,
                         jnp.where(onehot, cctx["lh_o"][fi, t],
                                   cctx["lh_p"][fi, t]),
                         cctx["lh_m"][fi, t])
        lc_c = jnp.where(cat_p1,
                         jnp.where(onehot, cctx["lc_o"][fi, t],
                                   cctx["lc_p"][fi, t]),
                         cctx["lc_m"][fi, t])
        lg = jnp.where(is_cat, lg_c, lg)
        lh = jnp.where(is_cat, lh_c, lh)
        lc = jnp.where(is_cat, lc_c, lc)
        # categorical sorted mode uses l2 + cat_l2 (hpp:233-246)
        l2_eff = jnp.where(is_cat & ~onehot, cctx["l2c"], l2)
        cat_words = _cat_left_bitset(fi, t, cat_p1, cctx, B)
    else:
        cat_words = jnp.zeros(NCAT_WORDS, jnp.int32)
    rg = sum_g - lg
    rh = sum_h2 - lh
    rc = ctx["num_data"] - lc

    # single-scan NaN edge: report default_left = False (hpp:103-106)
    single_nan = (~ctx["two_scan"][fi]) & (ctx["mt"][fi] == MISSING_NAN)
    default_left = is_dir2 & ~single_nan & ~is_cat

    has = jnp.isfinite(best_gain)
    out = SplitResult(
        gain=jnp.where(has, best_gain - min_gain_shift, KMIN_SCORE)
             * meta.penalty[fi],
        feature=jnp.where(has, fi, -1).astype(jnp.int32),
        threshold_bin=jnp.where(has, t, 0).astype(jnp.int32),
        default_left=default_left & has,
        left_output=calculate_leaf_output(lg, lh, l1, l2_eff, mds),
        right_output=calculate_leaf_output(rg, rh, l1, l2_eff, mds),
        left_count=lc,
        right_count=rc,
        left_sum_g=lg,
        left_sum_h=lh - KEPSILON,    # hpp: stores sum - kEpsilon
        right_sum_g=rg,
        right_sum_h=rh - KEPSILON,
        is_cat=is_cat & has,
        cat_words=jnp.where(is_cat & has, cat_words,
                            jnp.zeros(NCAT_WORDS, jnp.int32)),
    )
    return out
