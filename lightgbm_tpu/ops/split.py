"""Vectorized best-split search over histograms.

TPU-native counterpart of FeatureHistogram::FindBestThreshold*
(reference: src/treelearner/feature_histogram.hpp:76-653). The reference
scans each feature's bins twice (right-to-left with missing-default-left,
left-to-right with missing-default-right); here both scans over every
feature are evaluated at once as cumulative sums + masked argmax — an
ideal XLA workload (no data-dependent control flow).

Semantics preserved from the reference:
- L1-thresholded leaf outputs and gains (ThresholdL1 /
  CalculateSplittedLeafOutput / GetLeafSplitGainGivenOutput,
  feature_histogram.hpp:442-504).
- kEpsilon hessian regularization on each accumulated side and
  ``sum_hessian + 2*kEpsilon`` at the parent (feature_histogram.hpp:76-80).
- Missing handling: two-direction scans when ``num_bin > 2`` and missing
  is not None; NaN bin excluded from accumulation (rides with the default
  side); zero(default)-bin skipped when missing type is Zero
  (feature_histogram.hpp:87-110,506-653).
- min_data_in_leaf / min_sum_hessian_in_leaf / min_gain_to_split gates and
  monotone-constraint zeroing (GetSplitGains, feature_histogram.hpp:458).
- Tie-breaking: the flattened argmax order reproduces the reference's
  scan order (feature-major; dir=-1 before dir=+1; within dir=-1 larger
  thresholds win, within dir=+1 smaller thresholds win).
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

KEPSILON = 1e-15            # meta.h:38
KMIN_SCORE = -jnp.inf

MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2


class SplitParams(NamedTuple):
    """Static (per-training-run) split hyperparameters."""
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    max_delta_step: float = 0.0
    min_data_in_leaf: float = 20.0
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0


class FeatureMeta(NamedTuple):
    """Per-feature bin metadata as device arrays (host numpy accepted)."""
    num_bin: jax.Array       # [F] int32
    missing_type: jax.Array  # [F] int32
    default_bin: jax.Array   # [F] int32
    monotone: jax.Array      # [F] int32 (-1, 0, +1)
    penalty: jax.Array       # [F] float32 (feature_contri; 1.0 default)

    @classmethod
    def from_mappers(cls, mappers, monotone_constraints=None,
                     feature_contri=None) -> "FeatureMeta":
        f = len(mappers)
        mono = np.zeros(f, np.int32)
        if monotone_constraints:
            mono[:len(monotone_constraints)] = monotone_constraints
        pen = np.ones(f, np.float32)
        if feature_contri:
            pen[:len(feature_contri)] = feature_contri
        return cls(
            num_bin=np.array([m.num_bin for m in mappers], np.int32),
            missing_type=np.array([m.missing_type for m in mappers], np.int32),
            default_bin=np.array([m.default_bin for m in mappers], np.int32),
            monotone=mono,
            penalty=pen,
        )


class SplitResult(NamedTuple):
    """Best split for one leaf — all scalars (SplitInfo analog,
    src/treelearner/split_info.hpp:17)."""
    gain: jax.Array
    feature: jax.Array
    threshold_bin: jax.Array
    default_left: jax.Array
    left_output: jax.Array
    right_output: jax.Array
    left_count: jax.Array
    right_count: jax.Array
    left_sum_g: jax.Array
    left_sum_h: jax.Array
    right_sum_g: jax.Array
    right_sum_h: jax.Array


def threshold_l1(s, l1):
    """ThresholdL1 (feature_histogram.hpp:442)."""
    return jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0)


def calculate_leaf_output(sum_g, sum_h, l1, l2, max_delta_step):
    """CalculateSplittedLeafOutput (feature_histogram.hpp:447)."""
    ret = -threshold_l1(sum_g, l1) / (sum_h + l2)
    if max_delta_step > 0.0:
        ret = jnp.clip(ret, -max_delta_step, max_delta_step)
    return ret


def leaf_split_gain_given_output(sum_g, sum_h, l1, l2, output):
    """GetLeafSplitGainGivenOutput (feature_histogram.hpp:500)."""
    sg_l1 = threshold_l1(sum_g, l1)
    return -(2.0 * sg_l1 * output + (sum_h + l2) * output * output)


def leaf_split_gain(sum_g, sum_h, l1, l2, max_delta_step):
    """GetLeafSplitGain (feature_histogram.hpp:495)."""
    out = calculate_leaf_output(sum_g, sum_h, l1, l2, max_delta_step)
    return leaf_split_gain_given_output(sum_g, sum_h, l1, l2, out)


def _candidate_tables(hist: jax.Array, sum_g, sum_h, num_data,
                      feature_mask: jax.Array, meta: FeatureMeta,
                      hp: SplitParams, can_split=True):
    """Gain tables for every (feature, direction, threshold) candidate.

    Returns (g2, g1, min_gain_shift, ctx) where g2/g1 are the masked
    gain tables [F, B] for dir=-1 / dir=+1 and ctx carries the
    left-accumulation arrays needed to reconstruct a SplitResult.
    """
    f32 = jnp.float32
    F, B, _ = hist.shape
    nb = meta.num_bin.astype(jnp.int32)            # [F]
    mt = meta.missing_type.astype(jnp.int32)       # [F]
    db = meta.default_bin.astype(jnp.int32)        # [F]
    mono = meta.monotone.astype(jnp.int32)         # [F]

    l1 = f32(hp.lambda_l1)
    l2 = f32(hp.lambda_l2)
    mds = float(hp.max_delta_step)

    sum_g = jnp.asarray(sum_g, f32)
    sum_h2 = jnp.asarray(sum_h, f32) + 2.0 * KEPSILON   # hpp:80
    num_data = jnp.asarray(num_data, f32)

    gain_shift = leaf_split_gain(sum_g, sum_h2, l1, l2, mds)
    min_gain_shift = gain_shift + f32(hp.min_gain_to_split)

    bidx = jnp.arange(B, dtype=jnp.int32)[None, :]  # [1, B]
    nb_c = nb[:, None]
    two_scan = (nb > 2) & (mt != MISSING_NONE)      # [F]
    use_na = two_scan & (mt == MISSING_NAN)
    skip_db = two_scan & (mt == MISSING_ZERO)

    # --- contributions entering the cumulative scans --------------------
    valid_bin = bidx < nb_c
    zero_bin = (skip_db[:, None] & (bidx == db[:, None]))
    nan_bin = (use_na[:, None] & (bidx == nb_c - 1))
    contrib_mask = (valid_bin & ~zero_bin & ~nan_bin).astype(f32)  # [F, B]
    contrib = hist * contrib_mask[:, :, None]                      # [F, B, 3]

    cum = jnp.cumsum(contrib, axis=1)               # [F, B, 3] prefix sums
    tot = cum[:, -1, :]                             # [F, 3]

    # --- dir = +1 : left accumulates from bin 0 (default right) ---------
    l_g1 = cum[:, :, 0]
    l_h1 = cum[:, :, 1] + KEPSILON
    l_c1 = cum[:, :, 2]
    r_g1 = sum_g - l_g1
    r_h1 = sum_h2 - l_h1
    r_c1 = num_data - l_c1
    valid1 = (two_scan[:, None]
              & (bidx <= nb_c - 2)
              & ~(skip_db[:, None] & (bidx == db[:, None])))

    # --- dir = -1 : right accumulates from the top (default left) ------
    r_g2 = tot[:, None, 0] - cum[:, :, 0]
    r_h2 = tot[:, None, 1] - cum[:, :, 1] + KEPSILON
    r_c2 = tot[:, None, 2] - cum[:, :, 2]
    l_g2 = sum_g - r_g2
    l_h2 = sum_h2 - r_h2
    l_c2 = num_data - r_c2
    max_t2 = jnp.where(use_na, nb - 3, nb - 2)[:, None]  # dir=-1 can't emit nb-2
    valid2 = ((bidx <= max_t2)
              & (bidx >= 0)
              & ~(skip_db[:, None] & (bidx == db[:, None] - 1)))

    def side_gains(lg, lh, rg, rh):
        lo = calculate_leaf_output(lg, lh, l1, l2, mds)
        ro = calculate_leaf_output(rg, rh, l1, l2, mds)
        bad_mono = (((mono[:, None] > 0) & (lo > ro))
                    | ((mono[:, None] < 0) & (lo < ro)))
        g = (leaf_split_gain_given_output(lg, lh, l1, l2, lo)
             + leaf_split_gain_given_output(rg, rh, l1, l2, ro))
        return jnp.where(bad_mono, 0.0, g)

    def constraints(lc, lh, rc, rh):
        return ((lc >= hp.min_data_in_leaf) & (rc >= hp.min_data_in_leaf)
                & (lh >= hp.min_sum_hessian_in_leaf)
                & (rh >= hp.min_sum_hessian_in_leaf))

    gains1 = side_gains(l_g1, l_h1, r_g1, r_h1)
    ok1 = valid1 & constraints(l_c1, l_h1, r_c1, r_h1) & (gains1 > min_gain_shift)
    gains2 = side_gains(l_g2, l_h2, r_g2, r_h2)
    ok2 = valid2 & constraints(l_c2, l_h2, r_c2, r_h2) & (gains2 > min_gain_shift)

    fmask = feature_mask[:, None] & can_split
    g1 = jnp.where(ok1 & fmask, gains1, KMIN_SCORE)
    g2 = jnp.where(ok2 & fmask, gains2, KMIN_SCORE)
    ctx = dict(l_g1=l_g1, l_h1=l_h1, l_c1=l_c1,
               l_g2=l_g2, l_h2=l_h2, l_c2=l_c2,
               sum_g=sum_g, sum_h2=sum_h2, num_data=num_data,
               two_scan=two_scan, mt=mt, l1=l1, l2=l2, mds=mds)
    return g2, g1, min_gain_shift, ctx


def best_gain_per_feature(hist, sum_g, sum_h, num_data, feature_mask,
                          meta: FeatureMeta, hp: SplitParams,
                          can_split=True) -> jax.Array:
    """Per-feature best split gain [F] (-inf where no valid split) — the
    local-vote input of the voting-parallel learner
    (VotingParallelTreeLearner, voting_parallel_tree_learner.cpp:166)."""
    g2, g1, min_gain_shift, _ = _candidate_tables(
        hist, sum_g, sum_h, num_data, feature_mask, meta, hp, can_split)
    best = jnp.maximum(g2.max(axis=1), g1.max(axis=1))
    return jnp.where(jnp.isfinite(best),
                     (best - min_gain_shift) * meta.penalty, KMIN_SCORE)


def find_best_split(hist: jax.Array, sum_g, sum_h, num_data,
                    feature_mask: jax.Array, meta: FeatureMeta,
                    hp: SplitParams, can_split=True) -> SplitResult:
    """Find the best (feature, threshold, direction) for one leaf.

    Args:
      hist: [F, B, 3] histogram (grad, hess, count).
      sum_g/sum_h/num_data: leaf totals (scalars; num_data = bagged count).
      feature_mask: [F] bool — usable features (feature_fraction sampling,
        trivial-feature exclusion).
      can_split: scalar bool gate (e.g. max_depth reached) — forces -inf gain.
    """
    F, B, _ = hist.shape
    g2, g1, min_gain_shift, ctx = _candidate_tables(
        hist, sum_g, sum_h, num_data, feature_mask, meta, hp, can_split)

    # --- argmax with reference tie-break order --------------------------
    # flatten [F, 2, B]: dir=-1 first with REVERSED thresholds (so larger t
    # wins ties), then dir=+1 ascending. argmax returns first max.
    cand = jnp.stack([g2[:, ::-1], g1], axis=1)     # [F, 2, B]
    flat = cand.reshape(-1)
    idx = jnp.argmax(flat)
    best_gain = flat[idx]
    fi = idx // (2 * B)
    rem = idx % (2 * B)
    d = rem // B                                     # 0 -> dir=-1, 1 -> dir=+1
    tb = rem % B
    t = jnp.where(d == 0, B - 1 - tb, tb)            # undo reversal

    is_dir2 = d == 0
    lg = jnp.where(is_dir2, ctx["l_g2"][fi, t], ctx["l_g1"][fi, t])
    lh = jnp.where(is_dir2, ctx["l_h2"][fi, t], ctx["l_h1"][fi, t])
    lc = jnp.where(is_dir2, ctx["l_c2"][fi, t], ctx["l_c1"][fi, t])
    sum_g = ctx["sum_g"]
    sum_h2 = ctx["sum_h2"]
    l1, l2, mds = ctx["l1"], ctx["l2"], ctx["mds"]
    rg = sum_g - lg
    rh = sum_h2 - lh
    rc = ctx["num_data"] - lc

    # single-scan NaN edge: report default_left = False (hpp:103-106)
    single_nan = (~ctx["two_scan"][fi]) & (ctx["mt"][fi] == MISSING_NAN)
    default_left = is_dir2 & ~single_nan

    has = jnp.isfinite(best_gain)
    out = SplitResult(
        gain=jnp.where(has, best_gain - min_gain_shift, KMIN_SCORE)
             * meta.penalty[fi],
        feature=jnp.where(has, fi, -1).astype(jnp.int32),
        threshold_bin=jnp.where(has, t, 0).astype(jnp.int32),
        default_left=default_left & has,
        left_output=calculate_leaf_output(lg, lh, l1, l2, mds),
        right_output=calculate_leaf_output(rg, rh, l1, l2, mds),
        left_count=lc,
        right_count=rc,
        left_sum_g=lg,
        left_sum_h=lh - KEPSILON,    # hpp: stores sum - kEpsilon
        right_sum_g=rg,
        right_sum_h=rh - KEPSILON,
    )
    return out
