"""Process-wide geometry-keyed predict registry: the serving twin of
``ops/step_cache.py``.

Training got its cross-booster compiled-step registry in PR 5; this
module gives the PREDICT side the same treatment. The paper's workload
(lrb.py) retrains a fresh booster per sliding window and then *queries*
it on every request — inference latency, not training throughput, is
the million-users half of the north star. Before this module the
stacked predictor's dispatch was implicit: module-level ``jax.jit``
functions whose trace keys (array shapes + static offsets) happened to
collide across same-shaped models. That reuse was real but invisible
(no counters, no way to assert "the retrained window hit a warm
program") and fragile (any odd request batch size minted a fresh
trace).

Here the dispatch becomes a pure function of an explicit, hashable
**geometry key** — path kind (XLA scan / fused Pallas forest, with
the Pallas-Triton forest dispatching under its own "pallas-gpu" kind
so CPU-interpret and GPU-native programs never alias), the
32-bucketed per-feature table offsets (their sum is Wtot), padded
split/leaf axes, class count, tree-chunk and step counts, the row
bucket, the device kind — held in a bounded process-wide LRU:

- a retrained sliding-window model with the SAME geometry (same bucket
  widths — the 32-wide per-feature table buckets make this the common
  case) hits a warm entry: no re-trace, no recompile, and the hit is
  counted (``predict_cache/hits``);
- online micro-batches (1–4096 rows) pad to power-of-two **serve
  buckets** (``serve_bucket_rows``; floor 16, same pow2/16 taper as
  the training bucketer above 16k), so a live request stream touches a
  handful of compiled programs instead of one per distinct batch size.
  Padding is bit-exact: rows are independent in every predict kernel
  (per-row one-hot, per-row leaf match), pad rows are sliced off
  before the result leaves the device wrapper;
- forest (re)stacks are counted too (``predict_cache/stacks`` full
  host builds, ``predict_cache/extends`` incremental appends — see
  ``StackedModel.extend``), so "no full restack after retrain/continue"
  is assertable, not folklore.

Knobs (config.py): ``tpu_predict_cache`` (-1 auto = on / 0 off / 1 on)
and ``tpu_serve_bucket`` (-1 pow2 buckets / 0 exact shapes / N = round
up to a multiple of N). Counters land in the obs registry and are
exported by the PR-6 Prometheus exporter; ``stats()`` is snapshotted
into run reports and bench JSON (``meta.predict_cache``).
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, Dict, Optional

from ..obs import registry as obs
from ..obs import reqlog
from ..obs import trace

# bounded registry: one entry per distinct predict geometry; LRU evict
# keeps a model-shape sweep from pinning every dispatch wrapper forever
MAX_ENTRIES = 128

# smallest serve bucket: a 1-row online request pads to 16 rows of
# kernel work (noise) and every batch size 1..16 shares ONE compiled
# program; pow2 buckets above keep the program count logarithmic
SERVE_MIN_BUCKET = 16
# above this width, pow2/16 steps (8 buckets per octave) cap the pad
# at ~1/8 — same taper as step_cache.bucket_rows, serving-floor aside
_POW2_CAP = 1 << 14

_lock = threading.Lock()
_entries: "OrderedDict[tuple, Callable]" = OrderedDict()  # guarded-by: _lock
_mode = -1          # config.tpu_predict_cache  (-1 auto / 0 off / 1 on)
_bucket = -1        # config.tpu_serve_bucket   (-1 pow2 / 0 exact / N)


def configure(predict_cache: int = -1, serve_bucket: int = -1) -> None:
    """Install the config knobs (called from GBDT.init)."""
    global _mode, _bucket
    _mode = int(predict_cache)
    _bucket = int(serve_bucket)


def enabled() -> bool:
    """Registry bookkeeping active? (-1 auto = on. Off only disables
    the explicit registry + counters; jax's own trace cache still
    dedupes identical shapes.)"""
    return _mode != 0


def serve_bucket_rows(n: int, policy: Optional[int] = None) -> int:
    """Padded request-batch width for ``n`` rows under the serving
    bucket policy (``tpu_serve_bucket``; ``policy`` is the calling
    booster's own knob so one booster's config cannot re-shape another
    live booster's serving path).

    -1 (auto): next power of two >= max(n, SERVE_MIN_BUCKET) up to
    16384; above that pow2/16 steps (pad capped at ~1/8). Bit-exact by
    construction: predict kernels treat rows independently and the pad
    rows are sliced off on the way out.
    0: exact shapes (one trace per distinct batch size — the
    pre-registry behavior).
    N > 0: round up to a multiple of N.

    This is the serve-bucket seam of the request log: the chosen width
    is noted on the calling thread's active request context (free
    no-op otherwise), so the wide event a serving entry writes carries
    the bucket its batch dispatched at (obs/reqlog.py). Callers that
    clamp the answer (stacked_predict's row-chunk ceilings) re-note
    the clamped width — last note wins, and it is the truth."""
    b = _bucket_rows(int(n), policy)
    reqlog.note_bucket(b)
    return b


def _bucket_rows(n: int, policy: Optional[int]) -> int:
    p = (_bucket if policy is None else int(policy))
    if p == 0:
        return n
    if p > 0:
        return -(-n // p) * p
    b = max(n, SERVE_MIN_BUCKET)
    if b <= _POW2_CAP:
        return 1 << (b - 1).bit_length()
    return -(-b // (1 << ((b - 1).bit_length() - 4))) \
        * (1 << ((b - 1).bit_length() - 4))


def get(key: tuple, builder: Callable[[], Callable]) -> Callable:
    """Registry lookup: the process-wide predict dispatch for ``key``,
    building it on first encounter. A hit means a LATER model with the
    same geometry reuses the warm wrapper — and, because the key covers
    every static of the underlying jit, the warm compiled program."""
    if not enabled():
        return builder()
    with _lock:
        fn = _entries.get(key)
        if fn is not None:
            _entries.move_to_end(key)
            obs.counter("predict_cache/hits").add(1)
            trace.instant("predict_cache/hit", cat="cache")
            return fn
    obs.counter("predict_cache/misses").add(1)
    trace.instant("predict_cache/miss", cat="cache")
    fn = builder()
    with _lock:
        have = _entries.get(key)
        if have is not None:
            # lost race: functionally identical by key construction
            return have
        while len(_entries) >= MAX_ENTRIES:
            _entries.popitem(last=False)
            obs.counter("predict_cache/evictions").add(1)
        _entries[key] = fn
    return fn


def count_stack(trees: int) -> None:
    """Record one FULL host-side forest stack (StackedModel._build)."""
    obs.counter("predict_cache/stacks").add(1)
    obs.counter("predict_cache/stacked_trees").add(int(trees))
    trace.instant("predict_cache/stack", cat="cache")


def count_extend(trees: int) -> None:
    """Record one INCREMENTAL stack: only ``trees`` appended trees were
    tabled (StackedModel.extend) — the whole-ensemble rebuild the old
    ``_model_gen`` invalidation would have paid was skipped."""
    obs.counter("predict_cache/extends").add(1)
    obs.counter("predict_cache/stacked_trees").add(int(trees))
    trace.instant("predict_cache/extend", cat="cache")


def stats() -> Dict:
    """Snapshot for run reports / bench JSON (meta.predict_cache)."""
    with _lock:
        entries = len(_entries)
    return {
        "enabled": enabled(),
        "entries": entries,
        "hits": obs.counter("predict_cache/hits").value,
        "misses": obs.counter("predict_cache/misses").value,
        "evictions": obs.counter("predict_cache/evictions").value,
        "stacks": obs.counter("predict_cache/stacks").value,
        "extends": obs.counter("predict_cache/extends").value,
        "stacked_trees": obs.counter("predict_cache/stacked_trees").value,
    }


def clear() -> None:
    """Drop every cached dispatch (tests)."""
    with _lock:
        _entries.clear()
