"""On-device leaf-wise tree grower.

TPU-native counterpart of SerialTreeLearner::Train
(reference: src/treelearner/serial_tree_learner.cpp:157-221). The
reference's outer split loop runs on the host with pointer-juggled
histogram pools; here the ENTIRE tree build is one compiled XLA program:
a ``lax.fori_loop`` of ``num_leaves - 1`` shape-static steps, each doing

  1. pick the leaf with max split gain         (argmax over leaf table)
  2. apply the split to the partition          (masked select, O(N))
  3. build the histogram of the SMALLER child  (one-hot MXU contraction)
  4. sibling histogram by subtraction          (parent - smaller; hpp:68)
  5. best-split search for both children       (vectorized cumsum scan)

No host round-trips during growth; the histogram "pool"
(feature_histogram.hpp:655) becomes a preallocated HBM tensor
``[num_leaves, F, B, 3]`` indexed by leaf id.

Leaf numbering matches Tree::Split: at split ``i`` the left child keeps
the parent's leaf index and the right child becomes leaf ``i + 1``.
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .histogram import build_histogram
from .partition import apply_split
from .split import (FeatureMeta, SplitParams, SplitResult, KMIN_SCORE,
                    calculate_leaf_output, find_best_split)


class GrowerConfig(NamedTuple):
    """Static compile-time configuration of one grower."""
    num_leaves: int
    num_bins: int          # padded global B
    max_depth: int = -1
    chunk: int = 16384
    hp: SplitParams = SplitParams()


class TreeRecord(NamedTuple):
    """Device-side record of one grown tree (host builds a Tree from it)."""
    num_leaves: jax.Array          # scalar int32 — actual leaves
    split_leaf: jax.Array          # [L-1] parent leaf id per split (-1 unused)
    split_feature: jax.Array       # [L-1]
    split_bin: jax.Array           # [L-1] threshold in bin space
    split_gain: jax.Array          # [L-1]
    split_default_left: jax.Array  # [L-1] bool
    leaf_output: jax.Array         # [L] raw output (no shrinkage)
    leaf_count: jax.Array          # [L]
    leaf_sum_g: jax.Array          # [L]
    leaf_sum_h: jax.Array          # [L]
    internal_value: jax.Array      # [L-1] parent raw output at split time
    internal_count: jax.Array      # [L-1]
    split_is_cat: jax.Array        # [L-1] bool categorical split flag
    split_cat_words: jax.Array     # [L-1, 8] int32 left-set bin bitset


@jax.jit
def pack_record(rec: TreeRecord) -> jax.Array:
    """Flatten a TreeRecord into ONE [P] float32 array.

    Device→host transfers in this environment have high fixed latency per
    buffer, so the host materializes trees from a single stacked download
    (``jnp.stack([pack_record(r) for r in recs])``) instead of 12 small
    transfers per tree. float32 holds counts/bins exactly below 2^24.
    """
    f32 = jnp.float32
    # cat words carry full 32-bit patterns: split into exact 16-bit
    # halves (f32 holds ints < 2^24 exactly; a raw int32 would round).
    # Counts are split the same way: leaf_count can reach N, and above
    # 2^24 rows a single f32 would round it.
    w = rec.split_cat_words.astype(jnp.uint32)
    w_lo = jnp.bitwise_and(w, jnp.uint32(0xFFFF)).astype(f32)
    w_hi = jnp.right_shift(w, jnp.uint32(16)).astype(f32)

    def cnt_split(c):
        # round before the int cast: counts are f32 sums of ones and
        # can sit at 99.99999 (cast alone would truncate to 99)
        ci = jnp.round(c).astype(jnp.uint32)
        return (jnp.bitwise_and(ci, jnp.uint32(0xFFFF)).astype(f32),
                jnp.right_shift(ci, jnp.uint32(16)).astype(f32))
    lc_lo, lc_hi = cnt_split(rec.leaf_count)
    ic_lo, ic_hi = cnt_split(rec.internal_count)
    return jnp.concatenate([
        rec.num_leaves[None].astype(f32) if rec.num_leaves.ndim == 0
        else rec.num_leaves.astype(f32),
        rec.split_leaf.astype(f32),
        rec.split_feature.astype(f32),
        rec.split_bin.astype(f32),
        rec.split_gain.astype(f32),
        rec.split_default_left.astype(f32),
        rec.leaf_output.astype(f32),
        lc_lo, lc_hi,
        rec.leaf_sum_g.astype(f32),
        rec.leaf_sum_h.astype(f32),
        rec.internal_value.astype(f32),
        ic_lo, ic_hi,
        rec.split_is_cat.astype(f32),
        w_lo.reshape(-1),
        w_hi.reshape(-1),
    ])


def unpack_record(arr, num_leaves_cap: int) -> dict:
    """Inverse of pack_record on a host numpy [P] row -> dict of arrays."""
    L = num_leaves_cap
    s = L - 1
    import numpy as _np

    def cnt_join(lo, hi):
        return (_np.asarray(lo).astype(_np.int64)
                + (_np.asarray(hi).astype(_np.int64) << 16)).astype(
                    _np.float64)
    parts = {}
    off = 0
    parts["num_leaves"] = int(round(float(arr[0]))); off = 1
    for name in ("split_leaf", "split_feature", "split_bin", "split_gain",
                 "split_default_left"):
        parts[name] = arr[off:off + s]; off += s
    parts["leaf_output"] = arr[off:off + L]; off += L
    lc_lo = arr[off:off + L]; off += L
    lc_hi = arr[off:off + L]; off += L
    parts["leaf_count"] = cnt_join(lc_lo, lc_hi)
    for name in ("leaf_sum_g", "leaf_sum_h"):
        parts[name] = arr[off:off + L]; off += L
    parts["internal_value"] = arr[off:off + s]; off += s
    ic_lo = arr[off:off + s]; off += s
    ic_hi = arr[off:off + s]; off += s
    parts["internal_count"] = cnt_join(ic_lo, ic_hi)
    parts["split_is_cat"] = arr[off:off + s] > 0.5; off += s
    w_lo = _np.asarray(arr[off:off + s * 8]).reshape(s, 8); off += s * 8
    w_hi = _np.asarray(arr[off:off + s * 8]).reshape(s, 8); off += s * 8
    parts["split_cat_words"] = (
        w_lo.astype(_np.int64)
        + (w_hi.astype(_np.int64) << 16)).astype(_np.uint32).astype(
            _np.int32)
    return parts


class _State(NamedTuple):
    leaf_ids: jax.Array
    hist: jax.Array            # [L, F, B, 3]
    # per-leaf best-split table (SplitResult fields, [L] each)
    t_gain: jax.Array
    t_feature: jax.Array
    t_bin: jax.Array
    t_default_left: jax.Array
    t_left_output: jax.Array
    t_right_output: jax.Array
    t_left_count: jax.Array
    t_right_count: jax.Array
    t_left_sum_g: jax.Array
    t_left_sum_h: jax.Array
    t_right_sum_g: jax.Array
    t_right_sum_h: jax.Array
    # per-leaf aggregates
    leaf_output: jax.Array
    leaf_count: jax.Array
    leaf_sum_g: jax.Array
    leaf_sum_h: jax.Array
    leaf_depth: jax.Array
    # split records
    rec: TreeRecord


def make_tree_grower(cfg: GrowerConfig, meta: FeatureMeta,
                     hist_fn=None, split_fn=None, col_fn=None,
                     reduce_fn=None, jit=True):
    """NOTE: this legacy strict leaf-wise grower is the numerical-only
    correctness oracle (tests/test_wave_ops.py W=1 parity); it does not
    thread categorical splits, so the search is compiled out."""
    cfg = cfg._replace(hp=cfg.hp._replace(has_cat=False))
    """Build a ``grow(bins, grad, hess, sample_mask, feature_mask)``.

    Injection seams for the parallel learners (SURVEY §2.2):
      hist_fn(bins, w) -> [F_hist, B, 3]    histogram of one leaf's rows
        (data-parallel: local hist + psum; feature-parallel: local
        feature slice only; voting: local hist, election in split_fn)
      split_fn(hist, sg, sh, nd, fmask, can) -> SplitResult with GLOBAL
        feature indices (feature-parallel: cross-device argmax; voting:
        top-k vote + elected psum + argmax)
      col_fn(bins, feat) -> [N_local] bin column for a global feature id
      reduce_fn(x) -> global sum of a locally-summed scalar
        (data/voting-parallel: lax.psum over the data axis)

    All default to the serial single-device implementations. ``jit=False``
    returns the raw traceable fn for wrapping in shard_map.
    """
    L = cfg.num_leaves
    B = cfg.num_bins
    hp = cfg.hp
    # device copies: numpy arrays can't be indexed by traced scalars
    meta = FeatureMeta(*[jnp.asarray(x) for x in meta])

    if hist_fn is None:
        def hist_fn(bins, w):
            return build_histogram(bins, w, num_bins=B, chunk=cfg.chunk)
    if split_fn is None:
        def split_fn(hist, sg, sh, nd, fmask, can):
            return find_best_split(hist, sg, sh, nd, fmask, meta, hp, can)
    if col_fn is None:
        def col_fn(bins, feat):
            return jnp.take(bins, feat, axis=1).astype(jnp.int32)
    if reduce_fn is None:
        def reduce_fn(x):
            return x

    def depth_ok(depth):
        if cfg.max_depth > 0:
            return depth < cfg.max_depth
        return jnp.bool_(True)

    def _store_split(state: _State, leaf, res: SplitResult):
        return state._replace(
            t_gain=state.t_gain.at[leaf].set(res.gain),
            t_feature=state.t_feature.at[leaf].set(res.feature),
            t_bin=state.t_bin.at[leaf].set(res.threshold_bin),
            t_default_left=state.t_default_left.at[leaf].set(res.default_left),
            t_left_output=state.t_left_output.at[leaf].set(res.left_output),
            t_right_output=state.t_right_output.at[leaf].set(res.right_output),
            t_left_count=state.t_left_count.at[leaf].set(res.left_count),
            t_right_count=state.t_right_count.at[leaf].set(res.right_count),
            t_left_sum_g=state.t_left_sum_g.at[leaf].set(res.left_sum_g),
            t_left_sum_h=state.t_left_sum_h.at[leaf].set(res.left_sum_h),
            t_right_sum_g=state.t_right_sum_g.at[leaf].set(res.right_sum_g),
            t_right_sum_h=state.t_right_sum_h.at[leaf].set(res.right_sum_h),
        )

    def grow(bins, grad, hess, sample_mask, feature_mask):
        """Grow one tree.

        bins: [N, F] int bins; grad/hess: [N] f32 (already weighted);
        sample_mask: [N] f32 0/1 bagging membership;
        feature_mask: [F] bool usable features this tree.
        Returns (TreeRecord, leaf_ids[N]).
        """
        n, F = bins.shape
        f32 = jnp.float32
        grad = grad.astype(f32) * sample_mask
        hess = hess.astype(f32) * sample_mask
        w = jnp.stack([grad, hess, sample_mask.astype(f32)], axis=-1)

        # root
        root_hist = hist_fn(bins, w)
        root_g = reduce_fn(jnp.sum(grad))
        root_h = reduce_fn(jnp.sum(hess))
        root_c = reduce_fn(jnp.sum(sample_mask))
        root_split = split_fn(root_hist, root_g, root_h, root_c,
                              feature_mask, depth_ok(jnp.int32(0)))
        F_h = root_hist.shape[0]   # features held in the histogram pool

        state = _State(
            leaf_ids=jnp.zeros(n, jnp.int32),
            hist=jnp.zeros((L, F_h, B, 3), f32).at[0].set(root_hist),
            t_gain=jnp.full(L, KMIN_SCORE, f32).at[0].set(root_split.gain),
            t_feature=jnp.zeros(L, jnp.int32).at[0].set(root_split.feature),
            t_bin=jnp.zeros(L, jnp.int32).at[0].set(root_split.threshold_bin),
            t_default_left=jnp.zeros(L, bool).at[0].set(root_split.default_left),
            t_left_output=jnp.zeros(L, f32).at[0].set(root_split.left_output),
            t_right_output=jnp.zeros(L, f32).at[0].set(root_split.right_output),
            t_left_count=jnp.zeros(L, f32).at[0].set(root_split.left_count),
            t_right_count=jnp.zeros(L, f32).at[0].set(root_split.right_count),
            t_left_sum_g=jnp.zeros(L, f32).at[0].set(root_split.left_sum_g),
            t_left_sum_h=jnp.zeros(L, f32).at[0].set(root_split.left_sum_h),
            t_right_sum_g=jnp.zeros(L, f32).at[0].set(root_split.right_sum_g),
            t_right_sum_h=jnp.zeros(L, f32).at[0].set(root_split.right_sum_h),
            leaf_output=jnp.zeros(L, f32),
            leaf_count=jnp.zeros(L, f32).at[0].set(root_c),
            leaf_sum_g=jnp.zeros(L, f32).at[0].set(root_g),
            leaf_sum_h=jnp.zeros(L, f32).at[0].set(root_h),
            leaf_depth=jnp.zeros(L, jnp.int32),
            rec=TreeRecord(
                num_leaves=jnp.int32(1),
                split_leaf=jnp.full(L - 1, -1, jnp.int32),
                split_feature=jnp.full(L - 1, -1, jnp.int32),
                split_bin=jnp.zeros(L - 1, jnp.int32),
                split_gain=jnp.zeros(L - 1, f32),
                split_default_left=jnp.zeros(L - 1, bool),
                leaf_output=jnp.zeros(L, f32),
                leaf_count=jnp.zeros(L, f32),
                leaf_sum_g=jnp.zeros(L, f32),
                leaf_sum_h=jnp.zeros(L, f32),
                internal_value=jnp.zeros(L - 1, f32),
                internal_count=jnp.zeros(L - 1, f32),
                split_is_cat=jnp.zeros(L - 1, bool),
                split_cat_words=jnp.zeros((L - 1, 8), jnp.int32),
            ),
        )

        def body(i, state: _State):
            leaf = jnp.argmax(state.t_gain).astype(jnp.int32)
            gain = state.t_gain[leaf]
            can = gain > 0.0
            new = (i + 1).astype(jnp.int32)

            feat = state.t_feature[leaf]
            tbin = state.t_bin[leaf]
            dleft = state.t_default_left[leaf]
            bin_col = col_fn(bins, feat)
            leaf_ids = apply_split(
                state.leaf_ids, bin_col, leaf, new, tbin, dleft,
                meta.missing_type[feat], meta.default_bin[feat],
                meta.num_bin[feat], enabled=can)

            left_cnt = state.t_left_count[leaf]
            right_cnt = state.t_right_count[leaf]
            left_smaller = left_cnt <= right_cnt
            small_id = jnp.where(left_smaller, leaf, new)

            small_mask = (leaf_ids == small_id) & can
            w_small = w * small_mask[:, None].astype(f32)
            hist_small = hist_fn(bins, w_small)
            parent_hist = state.hist[leaf]
            hist_large = parent_hist - hist_small
            hist_left = jnp.where(left_smaller, hist_small, hist_large)
            hist_right = jnp.where(left_smaller, hist_large, hist_small)

            # child aggregates from the split record (leaf_splits.hpp:37)
            lg, lh = state.t_left_sum_g[leaf], state.t_left_sum_h[leaf]
            rg, rh = state.t_right_sum_g[leaf], state.t_right_sum_h[leaf]
            lo, ro = state.t_left_output[leaf], state.t_right_output[leaf]
            child_depth = state.leaf_depth[leaf] + 1

            # record the split
            rec = state.rec._replace(
                num_leaves=state.rec.num_leaves + can.astype(jnp.int32),
                split_leaf=state.rec.split_leaf.at[i].set(
                    jnp.where(can, leaf, -1)),
                split_feature=state.rec.split_feature.at[i].set(
                    jnp.where(can, feat, -1)),
                split_bin=state.rec.split_bin.at[i].set(tbin),
                split_gain=state.rec.split_gain.at[i].set(
                    jnp.where(can, gain, 0.0)),
                split_default_left=state.rec.split_default_left.at[i].set(dleft),
                internal_value=state.rec.internal_value.at[i].set(
                    calculate_leaf_output(
                        state.leaf_sum_g[leaf], state.leaf_sum_h[leaf],
                        hp.lambda_l1, hp.lambda_l2, hp.max_delta_step)),
                internal_count=state.rec.internal_count.at[i].set(
                    state.leaf_count[leaf]),
            )

            state = state._replace(
                leaf_ids=leaf_ids,
                hist=jnp.where(
                    can,
                    state.hist.at[leaf].set(hist_left).at[new].set(hist_right),
                    state.hist),
                leaf_output=jnp.where(
                    can,
                    state.leaf_output.at[leaf].set(lo).at[new].set(ro),
                    state.leaf_output),
                leaf_count=jnp.where(
                    can,
                    state.leaf_count.at[leaf].set(left_cnt).at[new].set(right_cnt),
                    state.leaf_count),
                leaf_sum_g=jnp.where(
                    can,
                    state.leaf_sum_g.at[leaf].set(lg).at[new].set(rg),
                    state.leaf_sum_g),
                leaf_sum_h=jnp.where(
                    can,
                    state.leaf_sum_h.at[leaf].set(lh).at[new].set(rh),
                    state.leaf_sum_h),
                leaf_depth=jnp.where(
                    can,
                    state.leaf_depth.at[leaf].set(child_depth)
                         .at[new].set(child_depth),
                    state.leaf_depth),
                rec=rec,
            )

            # child best splits
            can_l = can & depth_ok(child_depth)
            res_l = split_fn(hist_left, lg, lh, left_cnt, feature_mask, can_l)
            res_r = split_fn(hist_right, rg, rh, right_cnt, feature_mask, can_l)

            state = _store_split(state, leaf, SplitResult(
                *[jnp.where(can, a, b) for a, b in
                  zip(res_l, SplitResult(
                      gain=state.t_gain[leaf] * 0 + KMIN_SCORE,
                      feature=state.t_feature[leaf],
                      threshold_bin=state.t_bin[leaf],
                      default_left=state.t_default_left[leaf],
                      left_output=state.t_left_output[leaf],
                      right_output=state.t_right_output[leaf],
                      left_count=state.t_left_count[leaf],
                      right_count=state.t_right_count[leaf],
                      left_sum_g=state.t_left_sum_g[leaf],
                      left_sum_h=state.t_left_sum_h[leaf],
                      right_sum_g=state.t_right_sum_g[leaf],
                      right_sum_h=state.t_right_sum_h[leaf]))]))
            # note: when !can the leaf's gain is forced to -inf so the loop
            # terminates (all remaining gains <= 0 stay no-ops)
            res_r_guard = SplitResult(
                *[jnp.where(can, a, b) for a, b in
                  zip(res_r, SplitResult(
                      gain=jnp.asarray(KMIN_SCORE, f32),
                      feature=state.t_feature[new],
                      threshold_bin=state.t_bin[new],
                      default_left=state.t_default_left[new],
                      left_output=state.t_left_output[new],
                      right_output=state.t_right_output[new],
                      left_count=state.t_left_count[new],
                      right_count=state.t_right_count[new],
                      left_sum_g=state.t_left_sum_g[new],
                      left_sum_h=state.t_left_sum_h[new],
                      right_sum_g=state.t_right_sum_g[new],
                      right_sum_h=state.t_right_sum_h[new]))])
            state = _store_split(state, new, res_r_guard)
            return state

        state = jax.lax.fori_loop(0, L - 1, body, state)
        rec = state.rec._replace(
            leaf_output=state.leaf_output,
            leaf_count=state.leaf_count,
            leaf_sum_g=state.leaf_sum_g,
            leaf_sum_h=state.leaf_sum_h,
        )
        return rec, state.leaf_ids

    # jit-capture: ok(B, hp, meta, col_fn, hist_fn, reduce_fn,
    # split_fn, _store_split, depth_ok) — factory-scoped jit: every
    # capture derives from THIS factory call's cfg/meta/seam
    # callables, and callers cache per (booster, geometry); the
    # shared-step registry reaches this grower only through
    # build_train_step, whose geometry key covers cfg and meta.
    return jax.jit(grow) if jit else grow
