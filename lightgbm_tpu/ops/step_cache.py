"""Process-wide compiled-step registry: cross-booster reuse of the
fused training step.

The paper's core workload (lrb.py) trains a FRESH booster per sliding
window, and before this module every ``GBDT`` instance re-traced and
re-compiled its fused iteration step from scratch — the tier-1 suite
was compile-bound and BENCH_r05 paid 18.8 s of compile+iter0 against
112 s of training. The fix is the standard JAX serving/training
pattern: make the step a pure function of an explicit, hashable
**geometry key** and cache the resulting ``jax.jit`` callable
process-wide.

What had to move out of the per-instance closures to get there:

- **Feature metadata** (per-feature bin counts / missing types / ...):
  traced argument threaded through the grower (ops/wave_grower.py
  ``grow(..., meta=...)``) instead of factory-time constants — two
  boosters binned on different data share one trace.
- **Objective data** (labels, weights, renew targets): the objectives
  expose a pure ``gradient_builder()`` closing only over config
  scalars; the row-aligned arrays ride an ``aux`` pytree argument
  (objectives/objective.py).
- **The row count**: rows pad up to a power-of-two bucket
  (``tpu_row_bucket``) with a validity-mask argument zeroing the pad
  rows' gradients — boosters with different N share one compiled step
  bit-exactly (the pad rows carry exact +0.0 g/h and a zero bagging
  mask, so histograms, root aggregates, the integer salt of the
  stochastic-rounding stream, and renew percentiles are untouched).
- **The bin and feature axes**: the histogram width is the max
  OBSERVED bin count and trivial columns are excluded from F, so both
  drift with the data; B pads to the next power of two
  (``bucket_bins``) and F to a multiple of 8 with trivial pad
  features — every sliding window of the paper workload shares one
  geometry instead of recompiling per window.

The registry key covers everything that shapes the trace (learner
mode, mesh device ids, WaveGrowerConfig incl. split hyperparameters,
forced splits and the resolved histogram ``route`` — pallas-tpu /
pallas-gpu / fused-xla / two-pass, so the same geometry on a different
backend compiles its own program and a checkpoint restored onto
another device kind re-resolves and re-keys instead of replaying a
foreign kernel choice — valid-set slice layout, bins dtype/shape,
objective static key, aux structure, renew spec, sample-hook statics),
so a hit is guaranteed to be a functionally identical program. Ineligible
configurations (EFB bundles, feature/voting learners, RF's averaging
step, legacy-PRNG GOSS under ``tpu_goss_hash=0`` — its in-jit sampler
draws a positional PRNG stream whose values depend on the padded
width, so bucket-padded it would not be bit-exact) simply keep the
legacy per-instance closure — correctness first, reuse where it is
sound. Hashed GOSS (the default) samples on the shard-invariant
lowbias32 hash of the global row index and rides the shared step as a
traced mask; lambdarank rides its query tables as ``_``-keyed aux
arrays — both production modes hit the registry on same-geometry
retrains.

Counters land in the obs registry (``step_cache/hits|misses|
evictions``, ``step_cache/compile`` timer with per-key first-dispatch
wall time, ``step_cache/first_step_s`` per-booster spans recorded by
gbdt) and ``stats()`` is snapshotted into run reports
(``meta.step_cache``) and bench JSON.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, Optional

from ..obs import registry as obs
from ..obs import trace
from ..utils import log

# bounded registry: one entry per distinct training geometry; an LRU
# evict keeps pathological sweeps (e.g. a num_leaves grid search) from
# pinning every compiled executable forever
MAX_ENTRIES = 64

# smallest pow2 bucket the auto policy pads to: tiny test datasets
# share one step without ballooning (a 50-row set pads to 256 rows of
# zero-mask work — noise)
MIN_BUCKET = 256

_lock = threading.Lock()
_steps: "OrderedDict[tuple, Callable]" = OrderedDict()  # guarded-by: _lock
_mode = -1          # config.tpu_step_cache   (-1 auto / 0 off / 1 on)
_bucket = -1        # config.tpu_row_bucket   (-1 pow2 / 0 exact / N)


def configure(step_cache: int = -1, row_bucket: int = -1) -> None:
    """Install the config knobs (called from GBDT.init)."""
    global _mode, _bucket
    _mode = int(step_cache)
    _bucket = int(row_bucket)


def enabled() -> bool:
    """Cross-booster step reuse active? (-1 auto = on: the cache is a
    pure win on every backend — compiled steps are only shared between
    bit-identical programs.)"""
    return _mode != 0


def bucket_rows(n: int, align: int = 1, policy: Optional[int] = None) -> int:
    """Padded row-block width for ``n`` data rows under the bucketing
    policy, always a multiple of ``align`` (the learner's shard/chunk
    alignment unit). ``policy`` is the calling booster's own
    ``tpu_row_bucket`` — per-booster, so one booster's init cannot
    change another live booster's shape policy through the module
    globals (those remain only the default for config-less callers
    like the stacked predictor).

    -1 (auto): next power of two >= max(n, MIN_BUCKET) up to 16384;
    above that, pow2/16 steps — a pure pow2 pad could cost a single
    big-N booster up to 2x row work per iteration for a compile it
    amortizes only once, so the pad is capped at ~1/8 (still a
    log-bounded bucket count: 8 buckets per octave).
    0: exact shapes (only the alignment pad, the pre-cache behavior).
    N > 0: round up to a multiple of N. Note only tpu_row_bucket=0
    disables shape padding; tpu_step_cache=0 switches the TRAINING
    step back to per-booster closures but keeps predict-path
    bucketing (the pre-registry behavior).
    """
    align = max(int(align), 1)
    p = (_bucket if policy is None else int(policy))
    if p == 0:
        return _round_up(n, align)
    if p > 0:
        return _round_up(_round_up(n, p), align)
    return _round_up(pow2_bucket(n, MIN_BUCKET), align)


def shard_align_unit(n: int, D: int, kchunk: int) -> int:
    """Row-alignment unit of a D-device row-sharding learner
    (data/voting): shards chunk-align only when the data is large
    enough that the pad stays small (n >= 4*D*kchunk), else they
    align to the device count alone. The bucketed score width must be
    a multiple of this. ONE function for the grower's padding
    (models/gbdt.py _setup_grower) and the elastic-resume geometry
    (utils/checkpoint.py): resuming a checkpoint onto a DIFFERENT
    world size re-buckets the row block to the new world's unit —
    ``bucket_rows(n, shard_align_unit(n, D_new, kchunk), policy)`` IS
    the new shard width, and whether the transition is score-shape
    preserving is exactly whether old and new widths agree."""
    return D * kchunk if n >= 4 * D * kchunk else D


def pow2_bucket(x: int, floor: int) -> int:
    """THE shared shape-taper every bucketing discipline uses (score
    rows, sparse nnz planes, ingest entry planes): next power of two
    >= max(x, floor) up to 16384; above that, pow2/16 steps (8 buckets
    per octave) capping the pad at ~1/8."""
    b = max(int(x), int(floor))
    if b <= (1 << 14):
        return 1 << (b - 1).bit_length()
    return _round_up(b, 1 << ((b - 1).bit_length() - 4))


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def bucket_bins(b: int, policy: Optional[int] = None) -> int:
    """Padded histogram bin-axis width for ``b`` actual global bins.

    The grower's B dimension is the max OBSERVED per-feature bin count,
    which drifts with the data (a 256-row window sample bins to 51
    distinct values, the next to 46) — without padding, every sliding
    window of the paper workload is a fresh geometry and the registry
    never hits. Padding to the next power of two (floor 16, so the
    4-bit packed tier's B<=16 bound is never crossed by padding alone)
    is sound because the split finder masks per-feature via the TRACED
    ``meta.num_bin`` (bins >= num_bin contribute zero and their
    candidates are -inf), and histogram scatters never touch columns
    no bin value reaches. tpu_row_bucket=0 (exact shapes) disables
    this too — the knob means "no shape padding anywhere". ``policy``
    is the calling booster's own tpu_row_bucket (see bucket_rows)."""
    p = (_bucket if policy is None else int(policy))
    if p == 0:
        return b
    return 1 << (max(b, 16) - 1).bit_length()


def bucket_entries(e: int, policy: Optional[int] = None) -> int:
    """Padded sparse-coordinate length (the geometry key's nnz bucket)
    for ``e`` explicit entries: the sliding-window workload's windows
    carry different nnz, and without bucketing every window's sparse
    planes would be a fresh trace shape. Same policy shape as
    ``bucket_rows``: -1 (auto) next power of two (floor 1024) with
    pow2/16 steps above 16k; 0 exact; N > 0 multiples of N. Pad
    entries carry an out-of-range feature index, which every scatter
    in the sparse histogram drops (ops/hist_wave.py)."""
    p = (_bucket if policy is None else int(policy))
    if p == 0:
        return max(int(e), 1)
    if p > 0:
        return _round_up(max(int(e), 1), p)
    return pow2_bucket(e, 1024)


def aux_signature(aux) -> tuple:
    """Hashable structure+shape+dtype fingerprint of an aux pytree
    (nested dicts of arrays / None) — part of the geometry key, so two
    boosters only share a step when their traced aux trees match."""
    if aux is None:
        return ("none",)
    if isinstance(aux, dict):
        return tuple((k, aux_signature(aux[k])) for k in sorted(aux))
    return (tuple(getattr(aux, "shape", ())),
            str(getattr(aux, "dtype", type(aux).__name__)))


def get_step(key: tuple, builder: Callable[[], Callable]) -> Callable:
    """Registry lookup: return the process-wide compiled step for
    ``key``, building (and instrumenting) it on first encounter."""
    with _lock:
        fn = _steps.get(key)
        if fn is not None:
            _steps.move_to_end(key)
            obs.counter("step_cache/hits").add(1)
            trace.instant("step_cache/hit", cat="cache")
            return fn
    obs.counter("step_cache/misses").add(1)
    trace.instant("step_cache/miss", cat="cache")
    fn = _instrument(builder())
    with _lock:
        # lost race: another thread built it first — keep theirs
        # (functionally identical by key construction)
        have = _steps.get(key)
        if have is not None:
            return have
        while len(_steps) >= MAX_ENTRIES:
            _steps.popitem(last=False)
            obs.counter("step_cache/evictions").add(1)
        _steps[key] = fn
    return fn


def _instrument(fn: Callable) -> Callable:
    """Record the wall time of the first dispatch of a cached step —
    jit compiles synchronously on first call while the result stays
    async, so this span is trace+compile time to within dispatch
    noise."""
    state = {"first": True}

    def call(*args):
        if state["first"]:
            state["first"] = False
            t0 = time.monotonic()
            with trace.span("step_cache/compile", cat="cache"):
                out = fn(*args)
            dt = time.monotonic() - t0
            obs.timer("step_cache/compile").add(dt)
            log.debug("step cache: compiled a new fused step in %.2fs",
                      dt)
            return out
        return fn(*args)

    return call


def stats() -> Dict:
    """Snapshot for run reports / bench JSON (meta.step_cache)."""
    t = obs.timer("step_cache/compile")
    with _lock:
        entries = len(_steps)
    return {
        "enabled": enabled(),
        "entries": entries,
        "hits": obs.counter("step_cache/hits").value,
        "misses": obs.counter("step_cache/misses").value,
        "evictions": obs.counter("step_cache/evictions").value,
        "compile_s": round(t.total, 3),
        "compiles": t.count,
    }


def clear() -> None:
    """Drop every cached step (tests; frees the jit executables)."""
    with _lock:
        _steps.clear()


# ---------------------------------------------------------------------------
# The shared fused-step builder
# ---------------------------------------------------------------------------

def build_train_step(*, grower, K: int, n_score: int, n_total: int,
                     valid_slices: tuple, num_leaves: int,
                     grad_fn: Optional[Callable],
                     renew_alpha: Optional[float],
                     sample_hook: Optional[Callable]) -> Callable:
    """ONE jitted function for a full boosting iteration — the SINGLE
    step implementation (gradient -> K tree builds -> renew ->
    shrinkage -> score updates -> AddBias on the stored record) behind
    BOTH routing modes:

    - **registry path** (GBDT._get_cached_step): pure in its geometry —
      every data-dependent array (bins, scores, masks, labels via
      ``aux``, feature metadata via ``meta``, the row-validity mask
      ``rvalid``) is a traced argument, so the compiled program is
      shared by every booster with the same geometry key;
    - **legacy per-booster closure** (GBDT._get_step_fn for
      cache-ineligible configurations — GOSS's legacy positional
      sampler (tpu_goss_hash=0), EFB bundles, feature/voting
      learners, tpu_step_cache=0): the
      caller passes ``rvalid=None`` (exact row shapes, no validity
      mask) and ``meta=None`` (the grower consumes its own closure
      metadata), and the jitted step stays per-instance.

    One body, two callers: the stepcache parity suite
    (tests/test_step_cache.py) locks them together by construction
    instead of by a 60-line mirror.
    """
    import jax
    import jax.numpy as jnp

    from .predict import add_leaf_outputs

    pad_tail = n_total - n_score
    renew = renew_alpha is not None and grad_fn is not None
    if renew:
        from .renew import renew_leaf_outputs

    def step(bins, scores, valid_scores, mask, fmask, shrink,
             init_bias, g_in, h_in, key, rvalid, meta, aux):
        if grad_fn is None:
            g_all, h_all = g_in, h_in
        else:
            g_all, h_all = grad_fn(scores if K > 1 else scores[0],
                                   aux["obj"])
            if K == 1:
                g_all, h_all = g_all[None, :], h_all[None, :]
        if rvalid is not None:
            # pad rows: exact +0.0 g/h (a multiply by the zero mask
            # would produce -0.0 for negative gradients, perturbing the
            # integer bit-sum salt of the quantized stochastic-rounding
            # stream)
            g_all = jnp.where(rvalid[None, :], g_all, 0.0)
            h_all = jnp.where(rvalid[None, :], h_all, 0.0)
        if sample_hook is not None:
            # in-jit gradient-based sampling (GOSS): may amplify g/h
            # and shrink the bagging mask, all device-side. The hook
            # receives rvalid (None on the legacy route) so the hashed
            # sampler derives the REAL row count from the traced
            # validity mask instead of a closure int — the registry
            # path stays pure in its geometry.
            g_all, h_all, mask = sample_hook(g_all, h_all, mask, key,
                                             rvalid)
        recs = []
        vs = list(valid_scores)
        for k in range(K):
            g_k, h_k = g_all[k], h_all[k]
            if pad_tail:
                z = jnp.zeros(pad_tail, jnp.float32)
                g_k = jnp.concatenate([g_k, z])
                h_k = jnp.concatenate([h_k, z])
            if meta is None:
                rec, leaf_full = grower(bins, g_k, h_k, mask, fmask)
            else:
                rec, leaf_full = grower(bins, g_k, h_k, mask, fmask,
                                        meta)
            leaf_ids = leaf_full[:n_score]
            if renew:
                # objective-driven leaf refit
                # (serial_tree_learner.cpp:780-818) against the
                # PRE-update scores; splitless trees stay all-zero (the
                # reference never renews a tree it is about to discard,
                # gbdt.cpp:393-409); bucket-pad rows carry zero weight
                # through ``mask`` and cannot shift the percentiles
                residual = aux["renew"]["label"] - scores[k]
                new_out = renew_leaf_outputs(
                    leaf_ids, residual, aux["renew"].get("w"),
                    num_leaves, renew_alpha, rec.leaf_output,
                    mask[:n_score])
                new_out = jnp.where(rec.num_leaves > 1, new_out,
                                    rec.leaf_output)
                rec = rec._replace(leaf_output=new_out)
            # fold shrinkage (Tree::Shrinkage, gbdt.cpp:371).
            # NOTE for resume/replay authors: XLA freely re-fuses this
            # fold into the score gather-add (contraction skips the
            # intermediate rounding), so the live score state is NOT
            # reproducible by replaying the saved leaf outputs —
            # checkpoint resume (utils/checkpoint.py) therefore saves
            # the score buffers themselves instead of replaying trees.
            rec = rec._replace(
                leaf_output=rec.leaf_output * shrink,
                internal_value=rec.internal_value * shrink)
            # out-of-bag rows included: the partition covers ALL rows
            scores = scores.at[k].set(add_leaf_outputs(
                scores[k], leaf_ids, rec.leaf_output, 1.0))
            for vi, (voff, vn) in enumerate(valid_slices):
                vleaf = leaf_full[voff:voff + vn]
                vs[vi] = vs[vi].at[k].set(add_leaf_outputs(
                    vs[vi][k], vleaf, rec.leaf_output, 1.0))
            # AddBias on the STORED record only (tree.h:151): the init
            # score already reached train/valid scores through
            # BoostFromAverage's AddScore, so the score updates above
            # use the un-biased outputs. For a splitless first tree
            # this also yields the reference's constant tree
            # (leaf0 = init, gbdt.cpp:378-396); biasing unused leaf
            # slots is harmless (leaf_ids never reference them).
            rec = rec._replace(
                leaf_output=rec.leaf_output + init_bias[k],
                internal_value=rec.internal_value + init_bias[k])
            recs.append(rec)
        return scores, tuple(vs), recs

    # jit-capture: ok(grower, grad_fn, sample_hook) — the three
    # callable seams. Registry-path callers pass callables that close
    # only over config scalars/statics, all covered by the geometry
    # key (obj.static_key(), _grower_cfg, learner mode); legacy
    # callers jit per booster, so a capture is that booster's own.
    return jax.jit(step, donate_argnums=(1, 2))
