"""Generate docs/Parameters.md from the Config dataclass.

Counterpart of the reference's parameter-docs generator
(reference: helpers/parameter_generator.py producing docs/Parameters.rst
from config.h comments): here the single source of truth is
``lightgbm_tpu/config.py`` — dataclass fields, their defaults, the
alias table, and the documented-substitution lists all come from the
live object, so the page can never drift from the code.

Run: ``python docs/generate_params.py`` (writes docs/Parameters.md).
"""
import dataclasses
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

from lightgbm_tpu.config import ALIAS_TABLE, Config  # noqa: E402


def main() -> None:
    cfg = Config()
    inv_alias = {}
    for alias, canon in ALIAS_TABLE.items():
        inv_alias.setdefault(canon, []).append(alias)

    lines = [
        "# Parameters",
        "",
        "Generated from `lightgbm_tpu/config.py` by "
        "`docs/generate_params.py` — do not edit by hand.",
        "",
        "Every parameter accepts the reference's aliases; names and "
        "defaults match the reference's `docs/Parameters.rst` except "
        "for the `tpu_*` additions (TPU execution knobs) and the "
        "documented substitutions listed at the end.",
        "",
        "| parameter | default | aliases |",
        "|---|---|---|",
    ]
    for f in dataclasses.fields(Config):
        if f.name.startswith("_"):
            continue
        default = getattr(cfg, f.name)
        aliases = ", ".join(sorted(inv_alias.get(f.name, []))) or "—"
        shown = repr(default) if default != "" else '""'
        lines.append(f"| `{f.name}` | `{shown}` | {aliases} |")

    lines += [
        "",
        "## Accepted-but-substituted parameters",
        "",
        "These reference parameters are accepted for compatibility; "
        "their role is played by the TPU design instead:",
        "",
    ]
    for key, why in Config._SUBSUMED.items():
        lines.append(f"- `{key}` — {why}")
    lines += [
        "",
        "## Accepted-but-unimplemented parameters",
        "",
    ]
    for key in Config._UNIMPLEMENTED:
        lines.append(f"- `{key}` — accepted, warns, has no effect yet")
    out = os.path.join(os.path.dirname(__file__), "Parameters.md")
    with open(out, "w") as fh:
        fh.write("\n".join(lines) + "\n")
    print(f"wrote {out}: {len(lines)} lines")


if __name__ == "__main__":
    main()
