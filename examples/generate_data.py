"""Generate the example datasets (the reference ships committed data
files under examples/*; this repo generates equivalent synthetic ones
so the examples are self-contained and the repo stays small).

Run once before using any example config:
    python examples/generate_data.py
"""
import os

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))


def _save(subdir, name, y, X, fmt="%.6g"):
    path = os.path.join(HERE, subdir, name)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    np.savetxt(path, np.column_stack([y, X]), delimiter="\t", fmt=fmt)
    return path


def binary(rng):
    def make(n, seed_shift=0):
        X = rng.normal(size=(n, 28))
        logit = (X[:, 0] * X[:, 1] + 0.5 * X[:, 2] - 0.3 * X[:, 3]
                 + 0.2 * np.abs(X[:, 4]))
        y = (logit + 0.4 * rng.normal(size=n) > 0).astype(int)
        return y, X
    _save("binary_classification", "binary.train", *make(7000))
    _save("binary_classification", "binary.test", *make(500))


def regression(rng):
    def make(n):
        X = rng.normal(size=(n, 10))
        y = (X[:, 0] + 0.6 * X[:, 1] * X[:, 2] - 0.4 * X[:, 3] ** 2
             + 0.2 * rng.normal(size=n))
        return y, X
    _save("regression", "regression.train", *make(7000))
    _save("regression", "regression.test", *make(500))


def multiclass(rng):
    def make(n):
        X = rng.normal(size=(n, 12))
        score = np.stack([X[:, 0] + X[:, 1], X[:, 2] - X[:, 3],
                          X[:, 4] * X[:, 5], -X[:, 0] + X[:, 6],
                          0.5 * X[:, 7]], axis=1)
        y = np.argmax(score + 0.3 * rng.normal(size=score.shape), axis=1)
        return y, X
    _save("multiclass_classification", "multiclass.train", *make(7000))
    _save("multiclass_classification", "multiclass.test", *make(500))


def lambdarank(rng):
    def make(n_query, rows_per_q):
        n = n_query * rows_per_q
        X = rng.normal(size=(n, 8))
        rel = X[:, 0] + 0.5 * X[:, 1] + 0.3 * rng.normal(size=n)
        # graded relevance 0-4 per query
        y = np.zeros(n, int)
        for q in range(n_query):
            s = slice(q * rows_per_q, (q + 1) * rows_per_q)
            y[s] = np.clip(np.digitize(
                rel[s], np.quantile(rel[s], [0.5, 0.75, 0.9, 0.97])),
                0, 4)
        return y, X, np.full(n_query, rows_per_q, int)
    y, X, q = make(350, 20)
    _save("lambdarank", "rank.train", y, X)
    np.savetxt(os.path.join(HERE, "lambdarank", "rank.train.query"),
               q, fmt="%d")
    y, X, q = make(25, 20)
    _save("lambdarank", "rank.test", y, X)
    np.savetxt(os.path.join(HERE, "lambdarank", "rank.test.query"),
               q, fmt="%d")


def parallel(rng):
    # the parallel example reuses the binary task; the config switches
    # tree_learner (the reference's 2-machine socket walkthrough becomes
    # a one-process device-mesh run here)
    def make(n):
        X = rng.normal(size=(n, 28))
        logit = X[:, 0] * X[:, 1] + 0.5 * X[:, 2]
        y = (logit + 0.4 * rng.normal(size=n) > 0).astype(int)
        return y, X
    _save("parallel_learning", "binary.train", *make(7000))
    _save("parallel_learning", "binary.test", *make(500))


if __name__ == "__main__":
    rng = np.random.default_rng(7)
    binary(rng)
    regression(rng)
    multiclass(rng)
    lambdarank(rng)
    parallel(rng)
    print("example datasets written under", HERE)
