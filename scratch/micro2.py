"""Chained microbenchmarks: each iteration depends on the previous
output so queue overlap / caching can't fake the timing."""
import time

import numpy as np
import jax
import jax.numpy as jnp

N, F, B = 1_048_576, 28, 64
r = np.random.default_rng(0)
bins_np = r.integers(0, B, (N, F), dtype=np.uint8)
bins = jnp.asarray(bins_np)
w3 = jnp.asarray(r.normal(size=(N, 3)).astype(np.float32))
w96 = jnp.asarray(r.normal(size=(N, 96)).astype(np.float32))


def chain_time(name, step, w0, iters=20):
    """step: (bins, w) -> w (same shape). Chained through the loop."""
    f = jax.jit(step)
    w = f(bins, w0)
    jax.block_until_ready(w)
    t = time.perf_counter()
    w = w0
    for _ in range(iters):
        w = f(bins, w)
    jax.block_until_ready(w)
    dt = (time.perf_counter() - t) / iters
    print(f"{name}: {dt*1e3:.3f} ms")
    return dt


def hist_step(ncol, chunk=16384, dtype=jnp.float32):
    def step(bins, w):
        def body(acc, args):
            b, wc = args
            oh = jax.nn.one_hot(b, B, dtype=dtype)
            h = jnp.einsum("cfb,cd->fbd", oh, wc.astype(dtype),
                           preferred_element_type=jnp.float32)
            return acc + h, None
        bins_c = bins.astype(jnp.int32).reshape(-1, chunk, F)
        w_c = w.reshape(-1, chunk, ncol)
        init = jnp.zeros((F, B, ncol), jnp.float32)
        h, _ = jax.lax.scan(body, init, (bins_c, w_c))
        # fold hist back into w so the next iteration depends on it
        return w + jnp.sum(h) * 1e-30
    return step


print("devices:", jax.devices())
chain_time("(a) hist f32 3col   ", hist_step(3), w3)
chain_time("(b) hist f32 96col  ", hist_step(96), w96)
chain_time("(f) hist bf16 3col  ", hist_step(3, dtype=jnp.bfloat16), w3)
chain_time("(f) hist bf16 96col ", hist_step(96, dtype=jnp.bfloat16), w96)
chain_time("(a8) hist f32 3c c64k", hist_step(3, chunk=65536), w3)
chain_time("(b8) hist f32 96c c64k", hist_step(96, chunk=65536), w96)

# gather: chain idx -> gathered -> new idx
idx0 = jnp.asarray(r.integers(0, N, (N // 2,), dtype=np.int32))


def gather_step(bins, idx):
    rows = jnp.take(bins, idx, axis=0)          # [K, F] uint8
    return (idx + rows[:, 0].astype(jnp.int32)) % N


f = jax.jit(gather_step)
o = f(bins, idx0)
jax.block_until_ready(o)
t = time.perf_counter()
o = idx0
for _ in range(20):
    o = f(bins, o)
jax.block_until_ready(o)
print(f"(d) row gather N/2  : {(time.perf_counter()-t)/20*1e3:.3f} ms")

# partition pass chained
leaf0 = jnp.asarray(r.integers(0, 255, (N,), dtype=np.int32))
col = jnp.asarray(bins_np[:, 0].astype(np.int32))


def part_step(bins, leaf_ids):
    right = col > 31
    move = (leaf_ids == 7) & right
    return jnp.where(move, (leaf_ids + 1) % 255, leaf_ids)


f = jax.jit(part_step)
o = f(bins, leaf0)
jax.block_until_ready(o)
t = time.perf_counter()
o = leaf0
for _ in range(20):
    o = f(bins, o)
jax.block_until_ready(o)
print(f"(c) partition pass  : {(time.perf_counter()-t)/20*1e3:.3f} ms")
