"""Per-tree wall time of the wave grower at HIGGS-class size on TPU."""
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp

sys.path.insert(0, "/root/repo")
from lightgbm_tpu.ops.wave_grower import (WaveGrowerConfig,
                                          make_wave_grower)
from lightgbm_tpu.ops.predict import add_leaf_outputs
from lightgbm_tpu.ops.split import FeatureMeta, SplitParams

r = np.random.default_rng(0)
N, F, B, L = 1 << 20, 28, 63, 255
bins_t = r.integers(0, B, (F, N)).astype(np.uint8)
logit = (bins_t[0].astype(float) / B - 0.5
         + 0.3 * (bins_t[1] > 30) - 0.2 * (bins_t[2] < 10)
         + 0.1 * (bins_t[3] / B) * (bins_t[4] / B))
y = (logit + 0.3 * r.normal(size=N) > 0).astype(np.float32)
label = jnp.asarray(y)
bt = jnp.asarray(bins_t)
mask = jnp.ones(N, jnp.float32)
fmask = jnp.ones(F, bool)

meta = FeatureMeta(
    num_bin=np.full(F, B, np.int32),
    missing_type=np.zeros(F, np.int32),
    default_bin=np.zeros(F, np.int32),
    monotone=np.zeros(F, np.int32),
    penalty=np.ones(F, np.float32))

for W in (16, 25):
    grow = make_wave_grower(
        WaveGrowerConfig(num_leaves=L, num_bins=B, wave_size=W,
                         hp=SplitParams(min_data_in_leaf=20)),
        meta, jit=False)

    @jax.jit
    def train_step(scores, bt, label, mask, fmask):
        p = 1.0 / (1.0 + jnp.exp(-scores))
        grad = p - label
        hess = p * (1.0 - p)
        rec, leaf_ids = grow(bt, grad, hess, mask, fmask)
        return add_leaf_outputs(scores, leaf_ids,
                                rec.leaf_output * 0.1, 1.0), rec

    scores = jnp.zeros(N, jnp.float32)
    t0 = time.perf_counter()
    scores, rec = train_step(scores, bt, label, mask, fmask)
    float(np.asarray(scores[0]))
    print(f"W={W}: compile+first tree {time.perf_counter()-t0:.1f}s, "
          f"leaves={int(rec.num_leaves)}")

    def chain(iters):
        s = jnp.zeros(N, jnp.float32)
        for _ in range(iters):
            s, _ = train_step(s, bt, label, mask, fmask)
        float(np.asarray(s[0]))

    chain(2)
    t = time.perf_counter(); chain(3); t1 = time.perf_counter() - t
    t = time.perf_counter(); chain(13); t2 = time.perf_counter() - t
    dt = (t2 - t1) / 10
    rate = N * 1 / dt / 1e6
    print(f"W={W}: {dt*1e3:.1f} ms/tree -> {rate:.1f} M row-iters/s "
          f"(vs_baseline {rate/22.1:.2f})")
