"""Real timings on the axon tunnel: block_until_ready does NOT wait for
remote execution, so sync via a scalar download and difference two chain
lengths to cancel the fixed transfer latency."""
import time

import numpy as np
import jax
import jax.numpy as jnp

r = np.random.default_rng(0)
F, B = 28, 64


def chain_sync(f, args, w0, iters):
    w = f(*args, w0)
    float(np.asarray(jnp.sum(w)))  # warm + sync
    t = time.perf_counter()
    w = w0
    for _ in range(iters):
        w = f(*args, w)
    s = float(np.asarray(jnp.sum(w)))  # download forces completion
    return time.perf_counter() - t, s


def measure(name, f, args, w0, k1=4, k2=24, per_row=None):
    t1, _ = chain_sync(f, args, w0, k1)
    t2, _ = chain_sync(f, args, w0, k2)
    dt = (t2 - t1) / (k2 - k1)
    extra = ""
    if per_row:
        extra = f"  ({per_row / dt / 1e9:.0f} GB/s-equiv)"
    print(f"{name}: {dt*1e3:.3f} ms{extra}  [fixed={t1 - k1*dt:.3f}s]")
    return dt


def hist_step_maker(ncol, dtype=jnp.float32, chunk=16384):
    def hist_step(bins, w):
        def body(acc, args):
            b, wc = args
            oh = jax.nn.one_hot(b, B, dtype=dtype)
            h = jnp.einsum("cfb,cd->fbd", oh, wc.astype(dtype),
                           preferred_element_type=jnp.float32)
            return acc + h, None
        bins_c = bins.astype(jnp.int32).reshape(-1, chunk, F)
        w_c = w.reshape(-1, chunk, ncol)
        init = jnp.zeros((F, B, ncol), jnp.float32)
        h, _ = jax.lax.scan(body, init, (bins_c, w_c))
        return w + jnp.sum(h) * 1e-30
    return hist_step


NN = 1 << 20
bins = jnp.asarray(r.integers(0, B, (NN, F), dtype=np.uint8))
w3 = jnp.asarray(r.normal(size=(NN, 3)).astype(np.float32))
w96 = jnp.asarray(r.normal(size=(NN, 96)).astype(np.float32))

measure("hist f32  3col 1M", jax.jit(hist_step_maker(3)), (bins,), w3,
        per_row=NN * F)
measure("hist f32 96col 1M", jax.jit(hist_step_maker(96)), (bins,), w96,
        per_row=NN * F)
measure("hist bf16 3col 1M", jax.jit(hist_step_maker(3, jnp.bfloat16)),
        (bins,), w3, per_row=NN * F)
measure("hist bf16 96col 1M", jax.jit(hist_step_maker(96, jnp.bfloat16)),
        (bins,), w96, per_row=NN * F)

M = 4096
a32 = jnp.asarray(r.normal(size=(M, M)).astype(np.float32))
a16 = a32.astype(jnp.bfloat16)
dt = measure("matmul f32 4096", jax.jit(
    lambda a, w: jnp.dot(a, w, preferred_element_type=jnp.float32)),
    (a32,), a32)
print(f"   -> {2*M**3/dt/1e12:.1f} TFLOPS")
dt = measure("matmul bf16 4096", jax.jit(
    lambda a, w: jnp.dot(a, w, preferred_element_type=jnp.bfloat16)),
    (a16,), a16)
print(f"   -> {2*M**3/dt/1e12:.1f} TFLOPS")

leaf0 = jnp.asarray(r.integers(0, 255, (NN,), dtype=np.int32))
col = jnp.asarray(r.integers(0, B, (NN,), dtype=np.int32))


def part_step(col, leaf_ids):
    right = col > 31
    move = (leaf_ids == 7) & right
    return jnp.where(move, leaf_ids + 1, leaf_ids)


measure("partition 1M", jax.jit(part_step), (col,), leaf0, per_row=NN * 12)

idx0 = jnp.asarray(r.integers(0, NN, (NN // 2,), dtype=np.int32))


def gather_step(bins, idx):
    rows = jnp.take(bins, idx, axis=0)
    return (idx + rows[:, 0].astype(jnp.int32)) % NN


measure("row-gather N/2 1M", jax.jit(gather_step), (bins,), idx0,
        per_row=NN // 2 * F)
