"""Wave grower vs round-1 grower: W=1 tree equality on CPU."""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
import sys
sys.path.insert(0, "/root/repo")

import numpy as np
import jax
import jax.numpy as jnp

from lightgbm_tpu.ops.grower import GrowerConfig, make_tree_grower
from lightgbm_tpu.ops.wave_grower import (WaveGrowerConfig,
                                          make_wave_grower)
from lightgbm_tpu.ops.split import FeatureMeta, SplitParams

r = np.random.default_rng(0)
N, F, B, L = 5000, 10, 63, 31
bins = r.integers(0, B, (N, F)).astype(np.uint8)
logit = (bins[:, 0].astype(float) / B - 0.5 +
         0.3 * (bins[:, 1] > 30) - 0.2 * (bins[:, 2] < 10))
y = (logit + 0.3 * r.normal(size=N) > 0).astype(np.float32)
p = 0.5
grad = jnp.asarray(p - y)
hess = jnp.full(N, p * (1 - p), jnp.float32)
mask = jnp.asarray((r.random(N) < 0.8).astype(np.float32))
fmask = jnp.ones(F, bool)

meta = FeatureMeta(
    num_bin=np.full(F, B, np.int32),
    missing_type=np.zeros(F, np.int32),
    default_bin=np.zeros(F, np.int32),
    monotone=np.zeros(F, np.int32),
    penalty=np.ones(F, np.float32))
hp = SplitParams(min_data_in_leaf=20)

old = make_tree_grower(
    GrowerConfig(num_leaves=L, num_bins=B, chunk=N, hp=hp), meta)
rec_o, leaf_o = old(jnp.asarray(bins), grad, hess, mask, fmask)

for W in (1, 4, 16):
    new = make_wave_grower(
        WaveGrowerConfig(num_leaves=L, num_bins=B, wave_size=W, hp=hp),
        meta)
    rec_n, leaf_n = new(jnp.asarray(bins.T.copy()), grad, hess, mask,
                        fmask)
    nl_o, nl_n = int(rec_o.num_leaves), int(rec_n.num_leaves)
    same_feat = np.array_equal(np.asarray(rec_o.split_feature),
                               np.asarray(rec_n.split_feature))
    same_bin = np.array_equal(np.asarray(rec_o.split_bin),
                              np.asarray(rec_n.split_bin))
    same_leaf = np.array_equal(np.asarray(leaf_o), np.asarray(leaf_n))
    gmax = float(np.abs(np.asarray(rec_o.split_gain)
                        - np.asarray(rec_n.split_gain)).max())
    omax = float(np.abs(np.asarray(rec_o.leaf_output)
                        - np.asarray(rec_n.leaf_output)).max())
    print(f"W={W:2d}: leaves {nl_o}/{nl_n} feat_eq={same_feat} "
          f"bin_eq={same_bin} leaf_eq={same_leaf} dgain={gmax:.2e} "
          f"dout={omax:.2e}")
    if W == 1:
        assert same_feat and same_bin and same_leaf, "W=1 must match"
print("OK")
