"""Microbenchmarks for the round-2 perf redesign. Runs on the real TPU.

Measures the primitive costs that decide the grower architecture:
  (a) current 3-col one-hot einsum histogram (full data)
  (b) 96/128-col variant (wave-batched leaf channels)
  (c) masked partition pass (leaf_ids update)
  (d) row gather at various sizes
  (e) 1-D scatter (perm maintenance)
  (f) bf16 one-hot matmul
"""
import functools
import time

import numpy as np
import jax
import jax.numpy as jnp

N, F, B = 1_048_576, 28, 64
r = np.random.default_rng(0)
bins_np = r.integers(0, B, (N, F), dtype=np.uint8)
bins = jnp.asarray(bins_np)
w3 = jnp.asarray(r.normal(size=(N, 3)).astype(np.float32))
w96 = jnp.asarray(r.normal(size=(N, 96)).astype(np.float32))


def timeit(name, f, *a, iters=10):
    o = f(*a)
    jax.block_until_ready(o)
    t = time.perf_counter()
    for _ in range(iters):
        o = f(*a)
    jax.block_until_ready(o)
    dt = (time.perf_counter() - t) / iters
    print(f"{name}: {dt*1e3:.3f} ms")
    return dt


def make_hist(ncol, chunk=16384, dtype=jnp.float32):
    @jax.jit
    def hist(bins, w):
        def body(acc, args):
            b, wc = args
            oh = jax.nn.one_hot(b, B, dtype=dtype)  # [c, F, B]
            h = jnp.einsum("cfb,cd->fbd", oh, wc.astype(dtype),
                           preferred_element_type=jnp.float32)
            return acc + h, None
        bins_c = bins.astype(jnp.int32).reshape(-1, chunk, F)
        w_c = w.reshape(-1, chunk, ncol)
        init = jnp.zeros((F, B, ncol), jnp.float32)
        h, _ = jax.lax.scan(body, init, (bins_c, w_c))
        return h
    return hist


print("devices:", jax.devices())
timeit("(a) hist f32 3col  ", make_hist(3), bins, w3)
timeit("(b) hist f32 96col ", make_hist(96), bins, w96)
timeit("(f) hist bf16 3col ", make_hist(3, dtype=jnp.bfloat16), bins, w3)
timeit("(f) hist bf16 96col", make_hist(96, dtype=jnp.bfloat16), bins, w96)

# (c) partition pass: leaf_ids masked update + w-mask build
leaf_ids = jnp.asarray(r.integers(0, 255, (N,), dtype=np.int32))
col = jnp.asarray(bins_np[:, 0].astype(np.int32))


@jax.jit
def partition(leaf_ids, col):
    right = col > 31
    move = (leaf_ids == 7) & right
    return jnp.where(move, 255, leaf_ids)


timeit("(c) partition pass ", partition, leaf_ids, col)


@jax.jit
def wave_w(leaf_ids, g, h, small_ids):
    # [N, K*3] wave weight matrix build: per slot (leaf==small)*g/h/1
    m = (leaf_ids[:, None] == small_ids[None, :]).astype(jnp.float32)
    return jnp.concatenate([m * g[:, None], m * h[:, None], m], axis=1)


g = w3[:, 0]
h = w3[:, 1]
small_ids = jnp.arange(32, dtype=jnp.int32)
timeit("(c2) wave-w build 32", wave_w, leaf_ids, g, h, small_ids)

# (d) row gather
for frac, nm in ((2, "N/2"), (8, "N/8"), (32, "N/32")):
    k = N // frac
    idx = jnp.asarray(r.integers(0, N, (k,), dtype=np.int32))
    gf = jax.jit(lambda b, i: jnp.take(b, i, axis=0))
    timeit(f"(d) row gather {nm:5s}", gf, bins, idx)

# (e) 1-D scatter of N/2 int32
k = N // 2
pos = jnp.asarray(r.permutation(N)[:k].astype(np.int32))
val = jnp.asarray(r.integers(0, N, (k,), dtype=np.int32))


@jax.jit
def scatter1d(perm, pos, val):
    return perm.at[pos].set(val)


perm = jnp.arange(N, dtype=jnp.int32)
timeit("(e) scatter1d N/2  ", scatter1d, perm, pos, val)

# one-hot-free alternative: gather-from-hist-axis trick? measure a
# segment-sum formulation: sort-free bincount via one_hot is what we
# have; try jnp.zeros.at[bins,...].add (scatter-add) for reference
@jax.jit
def scatter_hist(bins_col, w):
    return jnp.zeros((B, 3), jnp.float32).at[bins_col].add(w)


timeit("(g) scatter-add hist 1 feat", scatter_hist, col, w3)
