"""Correctness + perf of wave histogram impls. Run on TPU (default env)."""
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp

sys.path.insert(0, "/root/repo")
from lightgbm_tpu.ops.hist_wave import (wave_histogram_pallas,
                                        wave_histogram_xla)

r = np.random.default_rng(0)


def ref_numpy(bins, g, h, leaf, wl, B):
    W = len(wl)
    F = bins.shape[1]
    out = np.zeros((W, F, B, 3), np.float32)
    for k, l in enumerate(wl):
        if l < 0:
            continue
        m = leaf == l
        for f in range(F):
            bc = np.bincount(bins[m, f], minlength=B)
            out[k, f, :, 2] = bc[:B]
            out[k, f, :, 0] = np.bincount(bins[m, f], weights=g[m],
                                          minlength=B)[:B]
            out[k, f, :, 1] = np.bincount(bins[m, f], weights=h[m],
                                          minlength=B)[:B]
    return out


def check(N, F, B, W, chunk, interpret):
    bins = r.integers(0, B, (N, F), dtype=np.uint8)
    g = r.normal(size=N).astype(np.float32)
    h = r.random(N).astype(np.float32)
    leaf = r.integers(-1, 8, N).astype(np.int32)
    wl = np.array([0, 3, -1, 7, 5][:W] + [2] * max(0, W - 5), np.int32)

    want = ref_numpy(bins, g, h, leaf, wl, B)
    bt = jnp.asarray(bins.T.copy())
    got_x = np.asarray(wave_histogram_xla(
        bt, jnp.asarray(g), jnp.asarray(h),
        jnp.asarray(leaf), jnp.asarray(wl), num_bins=B, chunk=512))
    err_x = np.abs(got_x - want).max()
    got_p = np.asarray(wave_histogram_pallas(
        bt, jnp.asarray(g), jnp.asarray(h),
        jnp.asarray(leaf), jnp.asarray(wl), num_bins=B, chunk=chunk,
        interpret=interpret))
    err_p = np.abs(got_p - want).max()
    print(f"N={N} F={F} B={B} W={W}: xla_err={err_x:.2e} "
          f"pallas_err={err_p:.2e}")
    assert err_x < 1e-3 and err_p < 1e-3


interp = jax.default_backend() != "tpu"
print("backend:", jax.default_backend(), "interpret:", interp)
check(1000, 7, 16, 5, 256, interp)
check(2048, 28, 63, 25, 512, interp)
check(513, 3, 255, 1, 256, interp)
check(4096, 12, 64, 25, 1024, interp)

if jax.default_backend() == "tpu":
    # perf at HIGGS-class size
    N, F, B = 1 << 20, 28, 64
    bins = jnp.asarray(r.integers(0, B, (F, N), dtype=np.uint8))
    g = jnp.asarray(r.normal(size=N).astype(np.float32))
    h = jnp.asarray(r.random(N).astype(np.float32))
    leaf = jnp.asarray(r.integers(0, 255, N).astype(np.int32))

    def run_chain(f, W, chunk, iters):
        wl = jnp.arange(W, dtype=jnp.int32)
        gg = g
        o = None
        for i in range(iters):
            o = f(bins, gg, h, leaf, wl, num_bins=B, chunk=chunk)
            gg = g + o[0, 0, 0, 0] * 1e-30
        float(np.asarray(o[0, 0, 0, 0]))

    def timed(f, W, chunk, k1=4, k2=24):
        run_chain(f, W, chunk, 2)   # warm/compile
        t = time.perf_counter(); run_chain(f, W, chunk, k1)
        t1 = time.perf_counter() - t
        t = time.perf_counter(); run_chain(f, W, chunk, k2)
        t2 = time.perf_counter() - t
        return (t2 - t1) / (k2 - k1)

    import functools as ft
    for prec in ("highest", "default"):
        for W in ((1, 25, 42) if prec == 'default' else (1, 16, 25)):
            for chunk in (1024, 2048, 4096):
                try:
                    f = ft.partial(wave_histogram_pallas, precision=prec)
                    dt = timed(f, W, chunk)
                    print(f"pallas {prec[:4]} W={W:2d} chunk={chunk}: {dt*1e3:.3f} ms")
                except Exception as e:
                    print(f"pallas {prec[:4]} W={W:2d} chunk={chunk}: FAIL "
                          f"{str(e).splitlines()[0][:90]}")
    for W in (1, 32):
        dt = timed(wave_histogram_xla, W, 65536)
        print(f"xla    W={W:2d}: {dt*1e3:.3f} ms")
