"""Scaling sanity checks: vary N; real compute must scale with N."""
import time

import numpy as np
import jax
import jax.numpy as jnp

r = np.random.default_rng(0)
F, B = 28, 64


def chain(f, args, w0, iters):
    w = f(*args, w0)
    jax.block_until_ready(w)
    t = time.perf_counter()
    w = w0
    for _ in range(iters):
        w = f(*args, w)
    jax.block_until_ready(w)
    return (time.perf_counter() - t) / iters


def hist_step(bins, w):
    def body(acc, args):
        b, wc = args
        oh = jax.nn.one_hot(b, B, dtype=jnp.float32)
        h = jnp.einsum("cfb,cd->fbd", oh, wc,
                       preferred_element_type=jnp.float32)
        return acc + h, None
    bins_c = bins.astype(jnp.int32).reshape(-1, 16384, F)
    w_c = w.reshape(-1, 16384, 3)
    init = jnp.zeros((F, B, 3), jnp.float32)
    h, _ = jax.lax.scan(body, init, (bins_c, w_c))
    return w + jnp.sum(h) * 1e-30


for NN in (1 << 20, 1 << 22):
    bins = jnp.asarray(r.integers(0, B, (NN, F), dtype=np.uint8))
    w3 = jnp.asarray(r.normal(size=(NN, 3)).astype(np.float32))
    dt = chain(jax.jit(hist_step), (bins,), w3, 20)
    print(f"hist  N={NN>>20}M: {dt*1e3:.3f} ms")

# plain elementwise pass over the same data for bandwidth reference
def ew_step(bins, w):
    s = jnp.sum(bins.astype(jnp.float32), axis=1)
    return w + (s[:, None] * 1e-30)


for NN in (1 << 20, 1 << 22):
    bins = jnp.asarray(r.integers(0, B, (NN, F), dtype=np.uint8))
    w3 = jnp.asarray(r.normal(size=(NN, 3)).astype(np.float32))
    dt = chain(jax.jit(ew_step), (bins,), w3, 20)
    gbs = (NN * F + NN * 12) / dt / 1e9
    print(f"ewise N={NN>>20}M: {dt*1e3:.3f} ms  ({gbs:.0f} GB/s)")

# matmul flops reference
for M in (2048, 4096):
    a = jnp.asarray(r.normal(size=(M, M)).astype(np.float32))
    def mm_step(a, w):
        return jnp.dot(a, w, preferred_element_type=jnp.float32)
    dt = chain(jax.jit(mm_step), (a,), a, 10)
    print(f"matmul f32 {M}: {dt*1e3:.3f} ms  ({2*M**3/dt/1e12:.1f} TFLOPS)")
    b16 = a.astype(jnp.bfloat16)
    def mm16_step(a, w):
        return jnp.dot(a, w, preferred_element_type=jnp.bfloat16)
    dt = chain(jax.jit(mm16_step), (b16,), b16, 10)
    print(f"matmul bf16 {M}: {dt*1e3:.3f} ms  ({2*M**3/dt/1e12:.1f} TFLOPS)")

# partition with col as ARG (no closure)
for NN in (1 << 20, 1 << 22):
    leaf0 = jnp.asarray(r.integers(0, 255, (NN,), dtype=np.int32))
    col = jnp.asarray(r.integers(0, B, (NN,), dtype=np.int32))
    def part_step(col, leaf_ids):
        right = col > 31
        move = (leaf_ids == 7) & right
        return jnp.where(move, leaf_ids + 1, leaf_ids)
    dt = chain(jax.jit(part_step), (col,), leaf0, 20)
    print(f"part  N={NN>>20}M: {dt*1e3:.3f} ms  ({NN*12/dt/1e9:.0f} GB/s)")
