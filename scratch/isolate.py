"""Isolate wave-hist kernel cost components: full kernel vs no-onehot
(constant oh) vs no-matmul (reduce oh) vs DMA-only."""
import functools
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

N, F, B, W = 1 << 20, 28, 64, 25
GS = 128 // B
GB = GS * B
GROUPS = -(-F // GS)
r = np.random.default_rng(0)
bins_t = jnp.asarray(r.integers(0, B, (F, N), dtype=np.uint8))
ghl = jnp.asarray(np.stack([
    r.normal(size=N), r.random(N), r.integers(0, 255, N),
    np.zeros(N)], axis=1).astype(np.float32))
wl = jnp.asarray(np.arange(W, dtype=np.float32)[None, :])
wlp = jnp.pad(wl, ((0, 0), (0, 128 - W)), constant_values=-1.0)


def make(mode, chunk):
    def kernel(wl_ref, bins_ref, ghl_ref, out_ref):
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _():
            out_ref[...] = jnp.zeros_like(out_ref)

        ghl_t = ghl_ref[...]
        gvec = ghl_t[:, 0:1]
        hvec = ghl_t[:, 1:2]
        lvec = ghl_t[:, 2:3]
        wlv = wl_ref[0, :]
        m = ((lvec == wlv[None, :]) & (wlv[None, :] >= 0.0))
        m = m.astype(jnp.float32)
        mw = m[:, :W]
        g_hi = gvec.astype(jnp.bfloat16).astype(jnp.float32)
        g_lo = gvec - g_hi
        h_hi = hvec.astype(jnp.bfloat16).astype(jnp.float32)
        h_lo = hvec - h_hi
        w_cols = jnp.concatenate(
            [mw * g_hi, mw * g_lo, mw * h_hi, mw * h_lo, mw], axis=1)
        w_cols = jnp.pad(w_cols, ((0, 0), (0, 128 - 5 * W)))

        ct = ghl_t.shape[0]
        row_iota = jax.lax.broadcasted_iota(jnp.int32, (GB, 1), 0)
        which_feat = row_iota // B
        which_bin = row_iota % B
        for p in range(GROUPS):
            if mode == "noonehot":
                oh_t = jnp.full((GB, ct), 1.0, jnp.float32)
            else:
                sel = jnp.full((GB, ct), -1, jnp.int32)
                for s in range(GS):
                    f = p * GS + s
                    if f < F:
                        row = bins_ref[f, :].astype(jnp.int32)
                        sel = jnp.where(which_feat == s, row[None, :], sel)
                oh_t = (sel == which_bin).astype(jnp.float32)
            if mode == "nomatmul":
                acc = jnp.broadcast_to(
                    jnp.sum(oh_t, axis=1, keepdims=True), (GB, 128))
            else:
                acc = jax.lax.dot_general(
                    oh_t, w_cols,
                    dimension_numbers=(((1,), (0,)), ((), ())),
                    precision=jax.lax.Precision.DEFAULT,
                    preferred_element_type=jnp.float32)
            if mode == "dmaonly":
                acc = acc * 0.0 + jnp.sum(ghl_t) + jnp.sum(
                    bins_ref[0, :].astype(jnp.int32).astype(jnp.float32))
            out_ref[p, :, :] += acc

    @jax.jit
    def run(bins_t, ghl):
        return pl.pallas_call(
            kernel,
            grid=(N // chunk,),
            in_specs=[
                pl.BlockSpec((1, 128), lambda i: (0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((F, chunk), lambda i: (0, i),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((chunk, 4), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((GROUPS, 128, 128),
                                   lambda i: (0, 0, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((GROUPS, 128, 128),
                                           jnp.float32),
        )(wlp, bins_t, ghl)
    return run


def timed(f, k1=4, k2=24):
    def chain(iters):
        x = ghl
        o = None
        for _ in range(iters):
            o = f(bins_t, x)
            x = ghl + o[0, 0, 0] * 1e-30
        float(np.asarray(o[0, 0, 0]))
    chain(2)
    t = time.perf_counter(); chain(k1); t1 = time.perf_counter() - t
    t = time.perf_counter(); chain(k2); t2 = time.perf_counter() - t
    return (t2 - t1) / (k2 - k1)


for chunk in (1024, 2048):
    for mode in ("full", "noonehot", "nomatmul", "dmaonly"):
        for trial in range(2):
            dt = timed(make(mode, chunk))
            print(f"chunk={chunk} {mode:9s}: {dt*1e3:.3f} ms")
