# R interface to lightgbm_tpu, mirroring the reference R package's API
# (reference: R-package/R/lgb.Dataset.R, lgb.train.R, lgb.cv.R,
# lgb.Booster.R — there the glue is src/lightgbm_R.cpp over the C API;
# here the glue is reticulate over the Python package, which fronts the
# same TPU engine).

.lgb_env <- new.env(parent = emptyenv())

.lgb_py <- function() {
  if (is.null(.lgb_env$mod)) {
    .lgb_env$mod <- reticulate::import("lightgbm_tpu", delay_load = FALSE)
  }
  .lgb_env$mod
}

#' Construct a Dataset (reference lgb.Dataset, R-package/R/lgb.Dataset.R)
#' @param data matrix / data.frame of features
#' @param label optional label vector
#' @param weight optional row weights
#' @param group optional query sizes (ranking)
#' @param colnames optional feature names
#' @param categorical_feature indices (1-based, R convention) or names
#' @param free_raw_data kept for API compatibility (ignored: the Python
#'   Dataset manages its own buffers)
#' @param ... extra dataset parameters (max_bin, ...)
#' @export
lgb.Dataset <- function(data, label = NULL, weight = NULL, group = NULL,
                        colnames = NULL, categorical_feature = NULL,
                        free_raw_data = TRUE, reference = NULL, ...) {
  lgb <- .lgb_py()
  params <- list(...)
  cat_py <- NULL
  if (!is.null(categorical_feature)) {
    # always a LIST: reticulate sends a length-1 vector as a python
    # scalar, which the python Dataset would silently ignore
    cat_py <- if (is.numeric(categorical_feature)) {
      as.list(as.integer(categorical_feature - 1L))  # 1-based -> 0-based
    } else {
      as.list(categorical_feature)
    }
  }
  ds <- lgb$Dataset(
    data = reticulate::r_to_py(as.matrix(data)),
    label = if (is.null(label)) NULL else as.numeric(label),
    weight = if (is.null(weight)) NULL else as.numeric(weight),
    group = if (is.null(group)) NULL else as.integer(group),
    feature_name = if (is.null(colnames)) "auto" else as.list(colnames),
    categorical_feature = if (is.null(cat_py)) "auto" else cat_py,
    params = params,
    reference = reference
  )
  class(ds) <- c("lgb.Dataset", class(ds))
  ds
}

#' Validation Dataset bound to a training Dataset's bin mappers
#' (reference lgb.Dataset.create.valid)
#' @export
lgb.Dataset.create.valid <- function(dataset, data, label = NULL, ...) {
  lgb.Dataset(data, label = label, reference = dataset, ...)
}

.as_booster <- function(bst) {
  class(bst) <- c("lgb.Booster", class(bst))
  bst
}

#' Train a model (reference lgb.train, R-package/R/lgb.train.R)
#' @param params list of parameters (objective, metric, num_leaves, ...)
#' @param data an lgb.Dataset
#' @param nrounds number of boosting rounds
#' @param valids named list of lgb.Dataset for evaluation
#' @param early_stopping_rounds stop when no metric improves this long
#' @param init_model path or Booster to continue from
#' @export
lgb.train <- function(params = list(), data, nrounds = 10,
                      valids = list(), obj = NULL, eval = NULL,
                      verbose = 1, record = TRUE, eval_freq = 1L,
                      init_model = NULL, early_stopping_rounds = NULL,
                      callbacks = list(), ...) {
  lgb <- .lgb_py()
  params <- c(params, list(...))
  if (!is.null(obj)) params$objective <- obj
  if (!is.null(eval)) params$metric <- eval
  evals_result <- if (record) reticulate::py_dict(list(), list())
                  else NULL
  bst <- lgb$train(
    params = params,
    train_set = data,
    num_boost_round = as.integer(nrounds),
    valid_sets = unname(valids),
    valid_names = if (length(valids)) names(valids) else NULL,
    init_model = init_model,
    early_stopping_rounds = if (is.null(early_stopping_rounds)) NULL
                            else as.integer(early_stopping_rounds),
    evals_result = evals_result,
    verbose_eval = if (verbose > 0) as.integer(eval_freq) else FALSE
  )
  bst <- .as_booster(bst)
  if (record) attr(bst, "record_evals") <- evals_result
  bst
}

#' Cross validation (reference lgb.cv, R-package/R/lgb.cv.R)
#' @param folds optional list of test-index vectors (1-based), one per
#'   fold — the reference's custom-folds path; overrides nfold
#' @export
lgb.cv <- function(params = list(), data, nrounds = 10, nfold = 3,
                   folds = NULL, stratified = TRUE,
                   early_stopping_rounds = NULL, verbose = 1, ...) {
  lgb <- .lgb_py()
  params <- c(params, list(...))
  folds_py <- NULL
  if (!is.null(folds)) {
    # reference semantics: each element is that fold's TEST indices
    # (1-based); the python cv complements them AFTER the dataset is
    # constructed with the merged params (constructing here to learn
    # num_data would freeze the bin mappers before cv's params apply)
    folds_py <- lapply(folds, function(test_idx)
      as.integer(test_idx - 1L))
  }
  res <- lgb$cv(
    params = params,
    train_set = data,
    num_boost_round = as.integer(nrounds),
    folds = folds_py,
    nfold = as.integer(nfold),
    stratified = stratified,
    early_stopping_rounds = if (is.null(early_stopping_rounds)) NULL
                            else as.integer(early_stopping_rounds),
    verbose_eval = verbose > 0
  )
  reticulate::py_to_r(res)
}

#' Field access on a Dataset (reference getinfo/setinfo,
#' R-package/R/lgb.Dataset.R): fields label, weight, init_score, group
#' @export
getinfo <- function(dataset, ...) UseMethod("getinfo")

#' @export
getinfo.lgb.Dataset <- function(dataset, name, ...) {
  v <- dataset$get_field(name)
  if (is.null(v)) NULL else as.numeric(reticulate::py_to_r(v))
}

#' @export
setinfo <- function(dataset, ...) UseMethod("setinfo")

#' @export
setinfo.lgb.Dataset <- function(dataset, name, info, ...) {
  if (identical(name, "group")) {
    dataset$set_field(name, as.integer(info))
  } else {
    dataset$set_field(name, as.numeric(info))
  }
  invisible(dataset)
}

#' Raw model serialization for R-native persistence (reference
#' lgb.Booster.R: saveRDS.lgb.Booster / readRDS.lgb.Booster): the
#' booster is captured as the LightGBM v2 model text, so the .rds file
#' round-trips through any R session with no live Python handle
#' @export
saveRDS.lgb.Booster <- function(object, file, ...) {
  raw_model <- reticulate::py_to_r(object$model_to_string())
  saveRDS(list(lgb_tpu_raw_model = raw_model), file = file, ...)
}

#' @export
readRDS.lgb.Booster <- function(file, ...) {
  obj <- readRDS(file, ...)
  stopifnot(!is.null(obj$lgb_tpu_raw_model))
  lgb.load(model_str = obj$lgb_tpu_raw_model)
}

#' Simplified one-call interface (reference lightgbm())
#' @export
lightgbm <- function(data, label = NULL, nrounds = 10,
                     params = list(), ...) {
  ds <- lgb.Dataset(data, label = label)
  lgb.train(params = params, data = ds, nrounds = nrounds, ...)
}

#' @export
predict.lgb.Booster <- function(object, data, rawscore = FALSE,
                                predleaf = FALSE, predcontrib = FALSE,
                                num_iteration = NULL, ...) {
  out <- object$predict(
    reticulate::r_to_py(as.matrix(data)),
    raw_score = rawscore, pred_leaf = predleaf,
    pred_contrib = predcontrib,
    num_iteration = if (is.null(num_iteration)) -1L
                    else as.integer(num_iteration))
  reticulate::py_to_r(out)
}

#' @export
print.lgb.Booster <- function(x, ...) {
  cat("<lightgbm_tpu Booster: ", x$num_trees(), " trees>\n", sep = "")
  invisible(x)
}

#' Save a model as the LightGBM v2 text format (reference lgb.save)
#' @export
lgb.save <- function(booster, filename, num_iteration = NULL) {
  booster$save_model(filename,
                     num_iteration = if (is.null(num_iteration)) -1L
                                     else as.integer(num_iteration))
  invisible(booster)
}

#' Load a text-format model — the reference's files load unchanged
#' (reference lgb.load)
#' @export
lgb.load <- function(filename = NULL, model_str = NULL) {
  lgb <- .lgb_py()
  bst <- lgb$Booster(model_file = filename, model_str = model_str)
  .as_booster(bst)
}

#' JSON dump (reference lgb.dump)
#' @export
lgb.dump <- function(booster, num_iteration = NULL) {
  booster$dump_model(num_iteration = if (is.null(num_iteration)) -1L
                                     else as.integer(num_iteration))
}

#' Feature importance (reference lgb.importance)
#' @param percentage rescale gains to fractions
#' @export
lgb.importance <- function(model, percentage = TRUE) {
  gain <- reticulate::py_to_r(model$feature_importance("gain"))
  split <- reticulate::py_to_r(model$feature_importance("split"))
  nm <- reticulate::py_to_r(model$feature_name())
  df <- data.frame(Feature = nm, Gain = as.numeric(gain),
                   Cover = NA_real_, Frequency = as.numeric(split))
  df <- df[order(-df$Gain), ]
  if (percentage && sum(df$Gain) > 0) {
    df$Gain <- df$Gain / sum(df$Gain)
    df$Frequency <- df$Frequency / max(sum(df$Frequency), 1)
  }
  df
}

#' Tree structure as a data.frame (reference lgb.model.dt.tree)
#' @export
lgb.model.dt.tree <- function(model, num_iteration = NULL) {
  dumped <- model$dump_model(
    num_iteration = if (is.null(num_iteration)) -1L
                    else as.integer(num_iteration))
  info <- reticulate::py_to_r(dumped)
  trees <- info$tree_info
  rows <- do.call(rbind, lapply(seq_along(trees), function(i) {
    flatten_node <- function(node, depth = 0L) {
      this <- data.frame(
        tree_index = i - 1L,
        depth = depth,
        split_feature = if (!is.null(node$split_feature))
          node$split_feature else NA_integer_,
        threshold = if (!is.null(node$threshold))
          as.numeric(node$threshold)[1] else NA_real_,
        split_gain = if (!is.null(node$split_gain))
          node$split_gain else NA_real_,
        value = if (!is.null(node$leaf_value))
          node$leaf_value else
          if (!is.null(node$internal_value)) node$internal_value
          else NA_real_,
        count = if (!is.null(node$leaf_count)) node$leaf_count else
          if (!is.null(node$internal_count)) node$internal_count
          else NA_real_
      )
      kids <- NULL
      for (k in c("left_child", "right_child")) {
        if (!is.null(node[[k]]) && is.list(node[[k]])) {
          kids <- rbind(kids, flatten_node(node[[k]], depth + 1L))
        }
      }
      rbind(this, kids)
    }
    flatten_node(trees[[i]]$tree_structure)
  }))
  rows
}

#' Per-prediction feature contribution breakdown (reference
#' lgb.interprete, R-package/R/lgb.interprete.R): for each row in
#' idxset, a data.frame of features ranked by their SHAP contribution
#' to that row's prediction.
#' @param model lgb.Booster
#' @param data feature matrix the rows are taken from
#' @param idxset 1-based row indices to interpret
#' @export
lgb.interprete <- function(model, data, idxset) {
  m <- as.matrix(data)[idxset, , drop = FALSE]
  contrib <- predict.lgb.Booster(model, m, predcontrib = TRUE)
  contrib <- as.matrix(contrib)
  nm <- reticulate::py_to_r(model$feature_name())
  nfeat <- length(nm)
  nclass <- ncol(contrib) %/% (nfeat + 1L)  # multiclass: K blocks
  lapply(seq_len(nrow(contrib)), function(i) {
    row <- contrib[i, ]
    df <- data.frame(Feature = c(nm, "BIAS"))
    for (k in seq_len(nclass)) {
      col <- if (nclass == 1L) "Contribution"
             else paste0("Contribution_class", k - 1L)
      off <- (k - 1L) * (nfeat + 1L)
      df[[col]] <- as.numeric(row[off + seq_len(nfeat + 1L)])
    }
    # rank by the largest-magnitude contribution across ALL classes
    # (the reference orders per class; a single cross-class order keeps
    # one data.frame per row while never sorting class k by class 0)
    mag <- do.call(pmax, c(lapply(df[-1L], abs), list(na.rm = TRUE)))
    df[order(-mag), ]
  })
}

#' Barplot of feature importance (reference lgb.plot.importance)
#' @param tree_imp output of lgb.importance
#' @param top_n number of features to show
#' @param measure "Gain" or "Frequency"
#' @export
lgb.plot.importance <- function(tree_imp, top_n = 10L,
                                measure = "Gain", ...) {
  top <- head(tree_imp[order(-tree_imp[[measure]]), ], top_n)
  # reversed so the largest bar is on top, like the reference's plot
  graphics::barplot(rev(top[[measure]]), names.arg = rev(top$Feature),
                    horiz = TRUE, las = 1, main = "Feature importance",
                    xlab = measure, ...)
  invisible(top)
}

#' Barplot of one prediction's contributions (reference
#' lgb.plot.interpretation)
#' @param tree_interpretation one element of lgb.interprete's output
#' @export
lgb.plot.interpretation <- function(tree_interpretation, top_n = 10L,
                                    ...) {
  top <- head(tree_interpretation, top_n)
  # column 2 is Contribution (binary/regression) or
  # Contribution_class0 (multiclass)
  graphics::barplot(rev(top[[2L]]), names.arg = rev(top$Feature),
                    horiz = TRUE, las = 1,
                    main = "Feature contribution", ...)
  invisible(top)
}

#' Save a Dataset to the binary cache format (reference
#' lgb.Dataset.save); reload by passing the file to lgb.Dataset's
#' Python loader via lgb.train(data = ...)
#' @export
lgb.Dataset.save <- function(dataset, fname) {
  dataset$save_binary(fname)
  invisible(dataset)
}

#' Row subset of a Dataset (reference slice.lgb.Dataset); idxset is
#' 1-based
#' @export
lgb.slice.Dataset <- function(dataset, idxset) {
  ds <- dataset$subset(as.list(as.integer(idxset - 1L)))
  class(ds) <- c("lgb.Dataset", class(ds))
  ds
}

#' Named evaluation log recorded by lgb.train(record = TRUE)
#' (reference lgb.get.eval.result)
#' @export
lgb.get.eval.result <- function(booster, data_name, eval_name) {
  rec <- attr(booster, "record_evals")
  if (is.null(rec)) stop("train with record = TRUE to collect evals")
  reticulate::py_to_r(rec)[[data_name]][[eval_name]]
}
